// Host-side Adagrad for the optimizer-offload tier.
//
// TPU-native replacement for the reference csrc/adagrad/cpu_adagrad.cpp
// (AVX-intrinsic Adagrad used by ZeRO-Offload): same capability — update
// fp32 master params resident in host RAM with the accumulated
// squared-gradient state — written as portable C++ whose inner loop the
// compiler vectorizes, parallelized with OpenMP. Mirrors the C ABI shape
// of cpu_adam.cpp (ctypes-friendly; no pybind11 in this image):
//
//   ds_adagrad_create(optimizer_id, alpha, eps, weight_decay)
//   ds_adagrad_update_lr(optimizer_id, alpha)
//   ds_adagrad_step(optimizer_id, step, n, params, grads, exp_avg_sq)
//   ds_adagrad_step_bf16grad(...): grads as uint16 bf16 words (the wire
//     format coming back from the chip) fused into the update.
//   ds_adagrad_destroy(optimizer_id)

#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>

namespace {

struct AdagradState {
  float alpha;
  float eps;
  float weight_decay;
};

std::map<int, AdagradState> g_optimizers;
std::mutex g_mu;

inline float bf16_to_f32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

template <typename GradLoader>
void adagrad_step_impl(const AdagradState& s, int64_t n, float* p,
                       GradLoader grad_at, float* vsq) {
  const float alpha = s.alpha, eps = s.eps, wd = s.weight_decay;

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grad_at(i);
    if (wd != 0.0f) g += wd * p[i];  // L2 into grad (reference semantics)
    float vi = vsq[i] + g * g;
    vsq[i] = vi;
    p[i] -= alpha * g / (std::sqrt(vi) + eps);
  }
}

}  // namespace

extern "C" {

int ds_adagrad_create(int optimizer_id, float alpha, float eps,
                      float weight_decay) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_optimizers[optimizer_id] = AdagradState{alpha, eps, weight_decay};
  return 0;
}

int ds_adagrad_update_lr(int optimizer_id, float alpha) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_optimizers.find(optimizer_id);
  if (it == g_optimizers.end()) return -1;
  it->second.alpha = alpha;
  return 0;
}

int ds_adagrad_step(int optimizer_id, int step, int64_t n, float* params,
                    const float* grads, float* exp_avg_sq) {
  (void)step;  // Adagrad has no bias correction; kept for ABI symmetry
  AdagradState s;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_optimizers.find(optimizer_id);
    if (it == g_optimizers.end()) return -1;
    s = it->second;
  }
  adagrad_step_impl(s, n, params,
                    [grads](int64_t i) { return grads[i]; }, exp_avg_sq);
  return 0;
}

int ds_adagrad_step_bf16grad(int optimizer_id, int step, int64_t n,
                             float* params, const uint16_t* grads_bf16,
                             float* exp_avg_sq) {
  (void)step;
  AdagradState s;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_optimizers.find(optimizer_id);
    if (it == g_optimizers.end()) return -1;
    s = it->second;
  }
  adagrad_step_impl(
      s, n, params,
      [grads_bf16](int64_t i) { return bf16_to_f32(grads_bf16[i]); },
      exp_avg_sq);
  return 0;
}

int ds_adagrad_destroy(int optimizer_id) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_optimizers.erase(optimizer_id);
  return 0;
}

}  // extern "C"
