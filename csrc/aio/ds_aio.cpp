// Async tensor I/O engine for the NVMe offload tier (ZeRO-Infinity).
//
// TPU-native replacement for the reference csrc/aio/ (libaio + O_DIRECT +
// pthread pool behind deepspeed_py_aio_handle.cpp). This image has no
// libaio/liburing headers, so the design is a std::thread worker pool doing
// positional pread/pwrite on O_DIRECT descriptors with aligned staging
// buffers — same capability surface: submit reads/writes of host buffers
// against files, overlap with compute, wait for completion.
//
// C ABI:
//   ds_aio_create(num_threads, block_size) -> handle id
//   ds_aio_pread(handle, fd-path, buffer, num_bytes, file_offset, async)
//   ds_aio_pwrite(handle, ...)
//   ds_aio_wait(handle) -> number of completed ops since last wait
//   ds_aio_destroy(handle)

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr size_t kAlign = 4096;  // O_DIRECT sector alignment

struct AioEngine {
  explicit AioEngine(int num_threads, int64_t block_size)
      : block_size_(block_size <= 0 ? (1 << 20) : block_size), stop_(false),
        inflight_(0), completed_(0), failed_(0) {
    for (int i = 0; i < num_threads; ++i)
      workers_.emplace_back([this] { Work(); });
  }

  ~AioEngine() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void Submit(std::function<bool()> fn) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      ++inflight_;
      queue_.push_back(std::move(fn));
    }
    cv_.notify_one();
  }

  // returns completed count since last Wait; negative on any failure
  int64_t Wait() {
    std::unique_lock<std::mutex> lock(mu_);
    done_cv_.wait(lock, [this] { return inflight_ == 0; });
    int64_t done = completed_;
    int64_t bad = failed_;
    completed_ = 0;
    failed_ = 0;
    return bad ? -bad : done;
  }

  int64_t block_size() const { return block_size_; }

 private:
  void Work() {
    for (;;) {
      std::function<bool()> job;
      {
        std::unique_lock<std::mutex> lock(mu_);
        cv_.wait(lock, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        job = std::move(queue_.front());
        queue_.pop_front();
      }
      bool ok = job();
      {
        std::lock_guard<std::mutex> lock(mu_);
        if (ok)
          ++completed_;
        else
          ++failed_;
        if (--inflight_ == 0) done_cv_.notify_all();
      }
    }
  }

  int64_t block_size_;
  std::vector<std::thread> workers_;
  std::deque<std::function<bool()>> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  bool stop_;
  int64_t inflight_;
  int64_t completed_;
  int64_t failed_;
};

std::map<int, AioEngine*> g_engines;
std::mutex g_engines_mu;
std::atomic<int> g_next_id{1};

// one blocking positional read/write, O_DIRECT when alignment permits,
// buffered fallback otherwise (reference deepspeed_aio_common.cpp behaves
// the same for unaligned tails).
bool DoIo(const std::string& path, char* buf, int64_t nbytes, int64_t offset,
          bool is_read, int64_t block_size) {
  bool aligned = (reinterpret_cast<uintptr_t>(buf) % kAlign == 0) &&
                 (nbytes % kAlign == 0) && (offset % kAlign == 0);
  int flags = is_read ? O_RDONLY : (O_WRONLY | O_CREAT);
#ifdef O_DIRECT
  if (aligned) flags |= O_DIRECT;
#endif
  int fd = open(path.c_str(), flags, 0644);
#ifdef O_DIRECT
  if (fd < 0 && aligned) {  // fs may not support O_DIRECT (tmpfs)
    flags &= ~O_DIRECT;
    fd = open(path.c_str(), flags, 0644);
  }
#endif
  if (fd < 0) return false;
  int64_t remaining = nbytes;
  int64_t pos = offset;
  char* p = buf;
  while (remaining > 0) {
    int64_t chunk = remaining < block_size ? remaining : block_size;
    ssize_t got = is_read ? pread(fd, p, chunk, pos) : pwrite(fd, p, chunk, pos);
    if (got <= 0) {
#ifdef O_DIRECT
      if (flags & O_DIRECT) {  // retry the tail buffered
        close(fd);
        flags &= ~O_DIRECT;
        fd = open(path.c_str(), flags, 0644);
        if (fd < 0) return false;
        continue;
      }
#endif
      close(fd);
      return false;
    }
    remaining -= got;
    pos += got;
    p += got;
  }
  close(fd);
  return true;
}

AioEngine* Get(int handle) {
  std::lock_guard<std::mutex> lock(g_engines_mu);
  auto it = g_engines.find(handle);
  return it == g_engines.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int ds_aio_create(int num_threads, int64_t block_size) {
  int id = g_next_id++;
  std::lock_guard<std::mutex> lock(g_engines_mu);
  g_engines[id] = new AioEngine(num_threads <= 0 ? 1 : num_threads, block_size);
  return id;
}

int ds_aio_pread(int handle, const char* path, char* buffer, int64_t nbytes,
                 int64_t offset, int async) {
  AioEngine* eng = Get(handle);
  if (!eng) return -1;
  std::string p(path);
  auto job = [=] { return DoIo(p, buffer, nbytes, offset, true,
                               eng->block_size()); };
  if (async) {
    eng->Submit(job);
    return 0;
  }
  return job() ? 0 : -1;
}

int ds_aio_pwrite(int handle, const char* path, char* buffer, int64_t nbytes,
                  int64_t offset, int async) {
  AioEngine* eng = Get(handle);
  if (!eng) return -1;
  std::string p(path);
  auto job = [=] { return DoIo(p, buffer, nbytes, offset, false,
                               eng->block_size()); };
  if (async) {
    eng->Submit(job);
    return 0;
  }
  return job() ? 0 : -1;
}

int64_t ds_aio_wait(int handle) {
  AioEngine* eng = Get(handle);
  if (!eng) return -1;
  return eng->Wait();
}

// aligned buffer helpers for O_DIRECT staging (reference pinned buffers)
void* ds_aio_alloc(int64_t nbytes) {
  void* out = nullptr;
  if (posix_memalign(&out, kAlign, static_cast<size_t>(nbytes)) != 0)
    return nullptr;
  return out;
}

void ds_aio_free(void* buf) { free(buf); }

int ds_aio_destroy(int handle) {
  AioEngine* eng = nullptr;
  {
    std::lock_guard<std::mutex> lock(g_engines_mu);
    auto it = g_engines.find(handle);
    if (it == g_engines.end()) return -1;
    eng = it->second;
    g_engines.erase(it);
  }
  delete eng;
  return 0;
}

}  // extern "C"
