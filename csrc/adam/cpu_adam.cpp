// Host-side Adam/AdamW for the optimizer-offload tier.
//
// TPU-native replacement for the reference csrc/adam/cpu_adam.cpp +
// csrc/includes/simd.h (AVX-intrinsic Adam used by ZeRO-Offload): same
// capability — update fp32 master params resident in host RAM while the
// accelerator holds only the working copy — but written as portable C++
// whose inner loops the compiler vectorizes (-O3 -march=native -ffast-math
// produces AVX2/AVX-512 fma loops), parallelized across cores with OpenMP.
//
// C ABI (ctypes-friendly; no pybind11 in this image):
//   ds_adam_create(optimizer_id, alpha, beta1, beta2, eps, weight_decay,
//                  adamw_mode)
//   ds_adam_step(optimizer_id, step, n, params, grads, exp_avg, exp_avg_sq)
//   ds_adam_step_bf16grad(...): same but grads given as uint16 bf16 words
//     (the wire format coming back from the chip) fused into the update.
//   ds_adam_destroy(optimizer_id)

#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>

namespace {

struct AdamState {
  float alpha;
  float beta1;
  float beta2;
  float eps;
  float weight_decay;
  bool adamw_mode;
};

std::map<int, AdamState> g_optimizers;
std::mutex g_mu;

inline float bf16_to_f32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

template <typename GradLoader>
void adam_step_impl(const AdamState& s, int step, int64_t n, float* p,
                    GradLoader grad_at, float* m, float* v) {
  const float bias1 = 1.0f - std::pow(s.beta1, static_cast<float>(step));
  const float bias2 = 1.0f - std::pow(s.beta2, static_cast<float>(step));
  const float step_size = s.alpha / bias1;
  const float denom_bias = std::sqrt(bias2);
  const float b1 = s.beta1, b2 = s.beta2, eps = s.eps, wd = s.weight_decay;
  const bool adamw = s.adamw_mode;

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grad_at(i);
    if (!adamw && wd != 0.0f) g += wd * p[i];  // L2 into grad (Adam mode)
    float mi = b1 * m[i] + (1.0f - b1) * g;
    float vi = b2 * v[i] + (1.0f - b2) * g * g;
    m[i] = mi;
    v[i] = vi;
    float update = (mi * step_size) / (std::sqrt(vi) / denom_bias + eps);
    if (adamw && wd != 0.0f) update += s.alpha * wd * p[i];  // decoupled
    p[i] -= update;
  }
}

}  // namespace

extern "C" {

int ds_adam_create(int optimizer_id, float alpha, float beta1, float beta2,
                   float eps, float weight_decay, int adamw_mode) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_optimizers[optimizer_id] =
      AdamState{alpha, beta1, beta2, eps, weight_decay, adamw_mode != 0};
  return 0;
}

int ds_adam_update_lr(int optimizer_id, float alpha) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_optimizers.find(optimizer_id);
  if (it == g_optimizers.end()) return -1;
  it->second.alpha = alpha;
  return 0;
}

int ds_adam_step(int optimizer_id, int step, int64_t n, float* params,
                 const float* grads, float* exp_avg, float* exp_avg_sq) {
  AdamState s;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_optimizers.find(optimizer_id);
    if (it == g_optimizers.end()) return -1;
    s = it->second;
  }
  adam_step_impl(s, step, n, params, [grads](int64_t i) { return grads[i]; },
                 exp_avg, exp_avg_sq);
  return 0;
}

int ds_adam_step_bf16grad(int optimizer_id, int step, int64_t n, float* params,
                          const uint16_t* grads_bf16, float* exp_avg,
                          float* exp_avg_sq) {
  AdamState s;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_optimizers.find(optimizer_id);
    if (it == g_optimizers.end()) return -1;
    s = it->second;
  }
  adam_step_impl(
      s, step, n, params,
      [grads_bf16](int64_t i) { return bf16_to_f32(grads_bf16[i]); }, exp_avg,
      exp_avg_sq);
  return 0;
}

// fp32 master -> bf16 working copy (round-to-nearest-even), the host half of
// the offload round trip back to the chip.
int ds_f32_to_bf16(int64_t n, const float* src, uint16_t* dst) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    std::memcpy(&bits, &src[i], sizeof(bits));
    uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
    dst[i] = static_cast<uint16_t>((bits + rounding) >> 16);
  }
  return 0;
}

int ds_adam_destroy(int optimizer_id) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_optimizers.erase(optimizer_id);
  return 0;
}

}  // extern "C"
