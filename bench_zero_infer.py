"""ZeRO-Inference benchmark: offload-streamed decode throughput.

The reference's ZeRO-Inference headline is tokens/s serving a model from
CPU offload (OPT-30B at 43 tok/s, ``docs/_posts/2022-09-10-zero-inference
.md:52``) — the regime is H2D-bandwidth-bound (one full model transfer per
decode step), so batch size and at-rest dtype set the rate. Prints ONE
JSON line::

    {"metric": "gpt2_zero_inference", "decode_tokens_per_sec": ...,
     "int8_tokens_per_sec": ..., "model_mb": ...}

On TPU: GPT-2 medium-ish config streamed bf16 and int8 from host RAM.
On CPU a tiny proxy keeps the script runnable anywhere.
"""

import sys
import time

import numpy as np

from deepspeed_tpu.utils.chip_probe import (arm_compilation_cache,
                                            assert_platform, emit_result,
                                            is_tpu,
                                            require_backend, resolve_metric,
                                            run_guarded)

METRIC = resolve_metric("gpt2_zero_inference", "gpt2_zero_inference_cpu_smoke")


def main():
    platform = require_backend(METRIC)

    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.inference.zero_inference import ZeroInferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    # window-proof: a flap re-exec replays compiles from the persistent
    # cache instead of burning the UP window recompiling
    arm_compilation_cache()
    assert_platform(METRIC, platform)
    on_tpu = is_tpu(platform)
    if on_tpu:
        # big enough that streaming dominates; batch amortizes each transfer.
        # The regime is H2D-bound (~seconds per decode step through the
        # axon tunnel's ~35-65 MB/s host link), so both the config and the
        # marginal window are sized to finish inside the backlog's 900s
        # budget (the 24x1024 first cut streamed 605 MB/step and timed
        # out); throughput is reported both raw and normalized to a
        # PCIe3-class link via the regime identity, so the smaller stack
        # loses no information
        cfg = GPT2Config(vocab_size=50257, n_positions=512, n_embd=768,
                         n_layer=12, n_head=12, dtype=jnp.bfloat16,
                         scan_layers=True)
        batch, prompt, new_tokens, reps = 32, 64, 2, 1
    else:
        cfg = GPT2Config.tiny(dtype=jnp.float32)
        batch, prompt, new_tokens, reps = 2, 8, 8, 2

    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, prompt)).astype(np.int32)
    zero = {"stage": 3, "offload_param": {"device": "cpu"}}

    # init ONCE on the host backend and share across both at-rest dtypes:
    # every decode step already streams the whole model, so two device
    # inits + pull-backs through a ~40 MB/s tunnel would cost more than
    # the measurement itself
    from deepspeed_tpu.inference.zero_inference import host_init_params

    params = host_init_params(model)
    print("# params initialized on host backend", file=sys.stderr,
          flush=True)

    def rate(dtype):
        t0 = time.perf_counter()
        eng = deepspeed_tpu.init_inference(
            model, dtype=dtype, zero=zero, params=params,
            max_out_tokens=cfg.n_positions)
        assert isinstance(eng, ZeroInferenceEngine)
        print(f"# {dtype} engine up in {time.perf_counter()-t0:.1f}s",
              file=sys.stderr, flush=True)

        # marginal decode cost between two generation lengths cancels
        # prefill + dispatch overhead (same methodology as
        # bench_decode.py). One warm generate at the LONGER length
        # compiles every program both timed lengths need (the KV cache is
        # sized by max_out_tokens, not by max_new_tokens)
        eng.generate(ids, max_new_tokens=2 * new_tokens)

        def gen_time(n):
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                eng.generate(ids, max_new_tokens=n)
                best = min(best, time.perf_counter() - t0)
            print(f"# {dtype} gen({n}): {best:.2f}s", file=sys.stderr,
                  flush=True)
            return best

        t1 = gen_time(new_tokens)
        t2 = gen_time(2 * new_tokens)
        per_token_s = max(1e-9, (t2 - t1) / new_tokens)
        return (batch / per_token_s, eng.total_param_bytes,
                eng.streamed_param_bytes)

    bf16_rate, model_bytes, streamed_bytes = rate(
        "bf16" if on_tpu else "fp32")
    # HEADLINE EMITTED NOW (VERDICT r5 #1 window-proofing): the int8
    # series and the h2d probe below are optional extras — a chip flap
    # during them can no longer zero the artifact. The final complete
    # line re-emits the same headline keys plus the extras; consumers
    # taking either the first or the last JSON line get a valid record.
    emit_result({
        "metric": METRIC,
        "decode_tokens_per_sec": round(bf16_rate, 1),
        "int8_tokens_per_sec": None,
        "model_mb": round(model_bytes / 1e6, 1),
        "batch": batch, "prompt": prompt, "new_tokens": new_tokens,
        "partial": "headline-early emit; int8/comm series follows",
    })
    # comm_compression series for the offload regime: the "wire" here is
    # the H2D link, and int8-at-rest halves the streamed bytes per step
    int8_rate, _, int8_streamed = rate("int8")

    out = {
        "metric": METRIC,
        "decode_tokens_per_sec": round(bf16_rate, 1),
        "int8_tokens_per_sec": round(int8_rate, 1),
        "model_mb": round(model_bytes / 1e6, 1),
        "batch": batch, "prompt": prompt, "new_tokens": new_tokens,
        "comm_compression": {
            "streamed_mb_per_step_bf16": round(streamed_bytes / 1e6, 1),
            "streamed_mb_per_step_int8": round(int8_streamed / 1e6, 1),
            "int8_tokens_per_sec": round(int8_rate, 1),
        },
    }
    if on_tpu:
        # measured host->device bandwidth: the regime's governing
        # constant (tokens/s ~= batch * bw / streamed_bytes). The
        # consuming reduction is compiled on a warmup buffer first, then
        # the timed window covers put + first consumption — device_put
        # is lazy through the axon tunnel, so only a consuming
        # execution pays the real transfer; on an eager runtime the
        # put has completed and the pre-compiled sum adds ~nothing.
        import jax

        dev = jax.devices()[0]
        shape = (64 * 1024 * 1024,)
        warm = jax.device_put(np.zeros(shape, np.uint8), dev)
        float(jnp.sum(warm[:8]))  # compile the consumer
        probe = np.ones(shape, np.uint8)
        t0 = time.perf_counter()
        buf = jax.device_put(probe, dev)
        float(jnp.sum(buf[:8]))
        h2d_mbps = probe.nbytes / 1e6 / (time.perf_counter() - t0)
        out["h2d_mbps"] = round(h2d_mbps, 1)
        # normalize out the host link: the reference's regime assumes a
        # local PCIe-class link (~16 GB/s gen3 x16). Computed from the
        # regime identity tokens/s = batch * bw / streamed_bytes using
        # the bytes each decode step actually streams — NOT the probe
        # above, which samples the (fluctuating) tunnel rate at a
        # different moment than the decode measurement did
        out["streamed_mb_per_step"] = round(streamed_bytes / 1e6, 1)
        out["projected_tokens_per_sec_at_16GBps_pcie3"] = round(
            batch * 16e9 / streamed_bytes, 1)
    emit_result(out)


if __name__ == "__main__":
    run_guarded(METRIC, main)
