"""FLOPs profiler.

Capability parity with the reference ``FlopsProfiler``
(``profiling/flops_profiler/profiler.py:17``), which monkey-patches
``torch.nn.functional`` to count MACs as ops execute (``:806,861``) and hangs
latency hooks on every module. Under XLA none of that is necessary or
meaningful: the compiler knows the exact FLOP count of the compiled program.
This profiler asks XLA (``jit(fn).lower(...).compile().cost_analysis()``)
and pairs it with measured step latency to report FLOPS utilisation, plus an
analytic per-component breakdown for transformer models (the reference's
per-module tree) derived from the model config rather than hooks.
"""

import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def number_to_string(num, units=None, precision=2):
    if units is None:
        if num >= 1e12:
            return f"{num / 1e12:.{precision}f} T"
        if num >= 1e9:
            return f"{num / 1e9:.{precision}f} G"
        if num >= 1e6:
            return f"{num / 1e6:.{precision}f} M"
        if num >= 1e3:
            return f"{num / 1e3:.{precision}f} K"
        return f"{num:.{precision}f} "
    return f"{num:.{precision}f} {units}"


def flops_to_string(flops, units=None, precision=2):
    return number_to_string(flops, units, precision) + "FLOPS"


def params_to_string(params_num, units=None, precision=2):
    return number_to_string(params_num, units, precision).rstrip() or "0"


def duration_to_string(duration, units=None, precision=2):
    if duration >= 1:
        return f"{duration:.{precision}f} s"
    if duration >= 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


def count_params(params) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)
                   if hasattr(l, "shape")))


class FlopsProfiler:
    """Profile a jitted step function.

    Usage (mirrors reference ``profiler.py`` API surface)::

        prof = FlopsProfiler(model=engine)
        prof.start_profile()
        engine.train_batch(batch=batch)     # or any fn via profile_fn
        prof.stop_profile()
        prof.print_model_profile()
    """

    def __init__(self, model=None, ds_engine=None):
        self.engine = ds_engine if ds_engine is not None else model
        self.started = False
        self._t0 = None
        self.flops = 0
        self.macs = 0
        self.params = 0
        self.duration = 0.0
        self.cost: Dict[str, float] = {}

    # ------------------------------------------------------------------
    def profile_fn(self, fn: Callable, *args, **kwargs):
        """Profile one callable: returns (flops, duration_s, cost_dict).

        Times the *compiled* executable (warm call), matching the program
        the FLOP count refers to.
        """
        jfn = jax.jit(fn)
        compiled = jfn.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        self.cost = dict(cost or {})
        self.flops = int(self.cost.get("flops", 0.0))
        self.macs = self.flops // 2
        jax.block_until_ready(jfn(*args, **kwargs))  # warm (compile cache)
        t0 = time.perf_counter()
        jax.block_until_ready(jfn(*args, **kwargs))
        self.duration = time.perf_counter() - t0
        return self.flops, self.duration, self.cost

    # reference start/stop surface around an engine step
    def start_profile(self, ignore_list=None):
        self.started = True
        if self.engine is not None and getattr(self.engine, "state", None) is not None:
            self.params = count_params(self.engine.state.params)
        self._t0 = time.perf_counter()

    def stop_profile(self):
        if not self.started:
            return
        self.duration = time.perf_counter() - self._t0
        eng = self.engine
        if eng is not None and getattr(eng, "_jit_micro", None) is not None \
                and getattr(eng, "state", None) is not None \
                and getattr(eng, "_last_batch", None) is not None:
            try:
                # lower through the engine's own jit wrapper so shardings/
                # donation match; one extra compile, paid only at the
                # profile step. Total step FLOPs = gas micro-steps + apply.
                gas = getattr(eng, "gradient_accumulation_steps", lambda: 1)()
                micro = eng._jit_micro.lower(
                    eng.state, eng._last_batch).compile().cost_analysis()
                if isinstance(micro, (list, tuple)):
                    micro = micro[0] if micro else {}
                self.cost = dict(micro or {})
                flops = int(self.cost.get("flops", 0.0)) * int(gas)
                if getattr(eng, "_jit_apply", None) is not None:
                    import jax.numpy as jnp

                    apply_cost = eng._jit_apply.lower(
                        eng.state, jnp.zeros((), jnp.float32)
                    ).compile().cost_analysis()
                    if isinstance(apply_cost, (list, tuple)):
                        apply_cost = apply_cost[0] if apply_cost else {}
                    flops += int((apply_cost or {}).get("flops", 0.0))
                self.flops = flops
                self.macs = flops // 2
            except Exception as e:  # cost analysis is best-effort
                logger.warning(f"flops cost analysis unavailable: {e}")
        self.started = False

    def end_profile(self):
        self.started = False

    def reset_profile(self):
        self.flops = self.macs = self.params = 0
        self.duration = 0.0
        self.cost = {}

    # ------------------------------------------------------------------
    def get_total_flops(self, as_string=False):
        return flops_to_string(self.flops) if as_string else self.flops

    def get_total_macs(self, as_string=False):
        return number_to_string(self.macs) + "MACs" if as_string else self.macs

    def get_total_params(self, as_string=False):
        return params_to_string(self.params) if as_string else self.params

    def get_total_duration(self, as_string=False):
        return duration_to_string(self.duration) if as_string else self.duration

    def print_model_profile(self, profile_step=1, module_depth=-1,
                            top_modules=1, detailed=True, output_file=None):
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler "
            "--------------------------",
            f"profile step:                   {profile_step}",
            f"params:                         {params_to_string(self.params)}",
            f"fwd+bwd flops (XLA measured):   {flops_to_string(self.flops)}",
            f"fwd+bwd MACs:                   {number_to_string(self.macs)}MACs",
            f"step latency:                   {duration_to_string(self.duration)}",
        ]
        if self.duration > 0 and self.flops:
            lines.append(
                f"achieved FLOPS:                 "
                f"{flops_to_string(self.flops / self.duration)}")
        for k in ("bytes accessed", "utilization"):
            if k in self.cost:
                lines.append(f"{k + ':':<32}{number_to_string(self.cost[k])}")
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report + "\n")
        else:
            logger.info("\n" + report)
        return report


def transformer_flops_per_token(n_params: int, n_layer: int, n_embd: int,
                                seq_len: int) -> Dict[str, float]:
    """Analytic transformer cost model (PaLM appendix / scaling-book form):
    fwd ≈ 2N + 2·L·T·d per token, train ≈ 3x fwd. The reference derives its
    per-module tree from hooks; on TPU the analytic form is what MFU math
    uses (bench.py)."""
    fwd = 2.0 * n_params + 2.0 * 2.0 * n_layer * seq_len * n_embd
    return {"fwd_flops_per_token": fwd,
            "train_flops_per_token": 3.0 * fwd}


def get_model_profile(model, input_shape=None, args=None, kwargs=None,
                      print_profile=True, detailed=True, module_depth=-1,
                      top_modules=1, warm_up=1, as_string=True,
                      output_file=None, ignore_modules=None, rng=None):
    """Standalone profile of a flax module (reference ``get_model_profile``,
    ``profiler.py:1139``): returns ``(flops, macs, params)``."""
    import jax.numpy as jnp

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if args is None:
        if input_shape is None:
            raise ValueError("provide input_shape or args")
        args = (jnp.zeros(input_shape, jnp.int32),)
    kwargs = kwargs or {}
    variables = model.init(rng, *args, **kwargs)
    params = count_params(variables)

    def fwd(v, *a):
        return model.apply(v, *a, **kwargs)

    prof = FlopsProfiler()
    flops, duration, _ = prof.profile_fn(fwd, variables, *args)
    prof.params = params
    if print_profile:
        prof.print_model_profile(detailed=detailed, module_depth=module_depth,
                                 top_modules=top_modules,
                                 output_file=output_file)
    macs = flops // 2
    if as_string:
        return (flops_to_string(flops), number_to_string(macs) + "MACs",
                params_to_string(params))
    return flops, macs, params
