"""Alias package (reference ``deepspeed/pipe/__init__.py``): user code
imports the pipeline building blocks from ``deepspeed.pipe``."""

from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec)

__all__ = ["PipelineModule", "LayerSpec", "TiedLayerSpec"]
