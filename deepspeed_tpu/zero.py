"""User-facing ``deepspeed_tpu.zero`` namespace.

Capability parity with ``deepspeed.zero`` (reference
``runtime/zero/__init__.py`` → ``partition_parameters.py``):

- :class:`Init` (ref ``:537``) — construct parameters DIRECTLY sharded so
  the full model never exists replicated on any chip. The reference hooks
  ``nn.Module.__init__`` to partition tensors as torch creates them; the
  TPU-native form jit-compiles the model's init function with ZeRO⊕TP
  ``out_shardings``, which is strictly stronger: XLA materializes each
  parameter shard in place, on device, with no transient full copy.
- :class:`GatheredParameters` (ref ``:1511``) — temporarily assemble
  partitioned parameters for host-side inspection/modification, writing
  modifications back to the sharded copies on exit.
- :func:`register_external_parameter` (ref ``:245``) — a documented no-op:
  it exists to keep the reference's forward hooks working when a module
  consumes another module's parameter; GSPMD has no hook machinery to
  break, cross-module reads just work.
"""

import contextlib
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


class Init:
    """Sharded parameter materialization (reference ``zero.Init``).

    Reference call shape::

        with deepspeed.zero.Init(config_dict_or_path=ds_config):
            model = MyModel()          # torch: tensors partitioned on creation

    TPU-native call shape (flax init is a function, not a side effect —
    there is nothing to intercept, so the materializer is explicit)::

        init = deepspeed_tpu.zero.Init(config_dict_or_path=ds_config)
        params = init.materialize(model, sample_batch)   # sharded jax.Arrays

    Inside ``deepspeed_tpu.initialize`` this already happens by default
    (engine ``_init_params``); the class exists for the reference's
    standalone uses — materializing a partitioned tree before or without
    an engine. The context-manager form is kept so reference-shaped code
    runs: entering is a no-op beyond recording the config.
    """

    def __init__(self, module=None, config_dict_or_path=None, mesh=None,
                 stage: int = 3, config=None, **unused):
        if config is not None and config_dict_or_path is None:
            config_dict_or_path = config  # reference's deprecated spelling
        self.config = config_dict_or_path
        self.stage = stage
        self._topo = mesh
        if unused:
            logger.warning(
                f"zero.Init: ignoring torch-runtime kwargs {sorted(unused)} "
                "(no meaning under XLA)")
        if module is not None:
            logger.warning(
                "zero.Init(module=...): post-hoc partitioning of a built "
                "module is the engine's job here — pass the model to "
                "deepspeed_tpu.initialize, or use materialize()")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _topology(self):
        from deepspeed_tpu.parallel.topology import get_topology

        return self._topo if self._topo is not None else get_topology()

    def materialize(self, model, sample_batch, rng=None, param_specs=None):
        """Init ``model``'s params with every leaf created ALREADY sharded
        (ZeRO stage-3 over the data axis layered on any TP base specs) —
        the jitted init's ``out_shardings`` place each shard on its
        device; no replicated copy ever exists (the property the
        reference's ``Init`` buys with creation-time partitioning)."""
        from deepspeed_tpu.runtime.zero.partition import build_zero_shardings

        topo = self._topology()
        rng = rng if rng is not None else jax.random.PRNGKey(42)
        abstract = jax.eval_shape(
            lambda r: model.init(r, sample_batch)["params"], rng)
        shardings, _ = build_zero_shardings(
            abstract, topo.mesh, stage=self.stage, param_specs=param_specs,
            persistence_threshold=0)
        init_fn = jax.jit(lambda r: model.init(r, sample_batch)["params"],
                          out_shardings=shardings)
        with topo.mesh:
            return init_fn(rng)


class GatheredParameters:
    """Temporarily assemble partitioned parameters (reference
    ``zero.GatheredParameters``, partition_parameters.py:1511)::

        with deepspeed_tpu.zero.GatheredParameters(params) as full:
            full["wte"][0] = 0.0          # host numpy, fully assembled
        # exit: modifications re-shard back onto the original placements

    ``params`` is any pytree of (possibly sharded) ``jax.Array`` leaves.
    The gathered form is a pytree of host numpy arrays. The default
    ``modifier_rank=None`` is read-only (the reference's default —
    "nobody writes"), so exit skips the write-back; pass
    ``modifier_rank=0`` to re-shard modifications and read them from
    ``.params`` after exit."""

    def __init__(self, params, modifier_rank: Optional[int] = None,
                 fwd_module=None, enabled: bool = True):
        del fwd_module  # reference registers external params; no-op here
        self._orig = params
        self._writeback = enabled and modifier_rank is not None
        self._enabled = enabled
        self._gathered = None
        self.params = params

    def __enter__(self):
        if not self._enabled:
            return self._orig
        self._gathered = jax.tree_util.tree_map(
            lambda leaf: np.array(jax.device_get(leaf)), self._orig)
        return self._gathered

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None or not self._enabled:
            return False
        if self._writeback:
            self.params = jax.tree_util.tree_map(
                lambda old, new: jax.device_put(
                    np.asarray(new, dtype=old.dtype), old.sharding),
                self._orig, self._gathered)
        return False


def register_external_parameter(module, parameter) -> None:
    """Reference ``register_external_parameter``
    (partition_parameters.py:245): tells the ZeRO-3 hook machinery that
    ``module``'s forward consumes a parameter owned elsewhere. Under
    GSPMD there are no gather hooks — any traced read of any sharded
    parameter compiles to the right collectives — so this is a no-op
    kept for import compatibility."""
    del module, parameter


# enum-shaped import compatibility (reference ZeroParamType/ZeroParamStatus)
class ZeroParamType:
    NORMAL = 1
    PARTITIONED = 2
    REMOTE = 3


class ZeroParamStatus:
    NOT_AVAILABLE = 1
    INFLIGHT = 2
    AVAILABLE = 3
