"""Continuous-batching scheduler: admission queue -> prefill -> decode
slots.

Pure host-side policy — no jax imports, so the tier-1 smoke tests run in
milliseconds. The device work (bucketed prefill programs, the fixed-slot
decode step) lives in :mod:`deepspeed_tpu.serving.engine`; this class
decides *which* request runs *where* and *when*:

- ``submit`` applies admission control: prompt must fit a bucket, queue
  depth is bounded, and (policy ``shed``) committed tokens — the
  worst-case ``prompt + max_new`` over queued + running work — must stay
  under ``max_inflight_tokens``. Policy ``queue`` accepts the request
  and defers slot admission instead.
- ``admit`` splices queued requests into free decode slots *between*
  decode steps: expired requests are shed from the head, block-pool
  backpressure defers admission (never drops — blocks free as running
  sequences finish), and each admitted request gets its block table.
  With a prefix cache attached, admission first matches the longest
  cached prefix: matched full blocks map read-only into the table
  (refcount++, nothing prefills twice), a matched partial tail is
  scheduled for copy-on-write, and the request carries how many prompt
  tokens its prefill may skip (``cached_len``).
- ``finish``/``shed`` return capacity (slot, blocks, token budget)
  immediately.
"""

import time
from collections import deque
from typing import List, Optional, Tuple

from deepspeed_tpu.serving import request as rq
from deepspeed_tpu.serving.blocks import BlockManager
from deepspeed_tpu.serving.config import (QUEUE, ServingConfig, bucket_for,
                                          resolve_buckets)
from deepspeed_tpu.telemetry.tracing import NULL_TRACER, to_ns


class ContinuousBatchingScheduler:
    def __init__(self, config: ServingConfig, blocks: BlockManager,
                 max_len: int, buckets: Optional[List[int]] = None,
                 clock=time.monotonic, prefix_cache=None, tracer=None):
        self.config = config
        self.blocks = blocks
        # optional PrefixCache: admission matches cached prompt prefixes
        # and maps their blocks in read-only instead of re-prefilling
        self.prefix = prefix_cache
        # span tracer (telemetry/tracing.py, host-only): admission emits
        # the submit->slot `queue` span and sheds emit `shed` spans into
        # the request's trace — the causal timeline the engine/router
        # continue. Inert (NULL_TRACER) unless tracing is configured.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.max_len = int(max_len)
        self.buckets = buckets if buckets is not None else resolve_buckets(
            config.prompt_buckets, self.max_len, floor=config.block_size)
        self.clock = clock
        self.queue: deque = deque()
        self.slots: List[Optional[rq.Request]] = [None] * config.decode_slots
        self.committed_tokens = 0  # worst-case prompt+max_new, queued+running
        self._live_ids = set()     # queued + running request ids
        self.stats = self._fresh_stats()

    @staticmethod
    def _fresh_stats():
        return {"submitted": 0, "admitted": 0, "finished": 0,
                "shed": 0, "shed_reasons": {}, "queue_peak": 0,
                "migrated_in": 0, "migrated_out": 0}

    def reset_stats(self):
        """Zero the counters (a bench epoch boundary); queue/slots/block
        accounting — the live state — is untouched."""
        self.stats = self._fresh_stats()

    # ------------------------------------------------------------------
    @staticmethod
    def _cost(req: rq.Request) -> int:
        return req.prompt_len + req.max_new_tokens

    def _deadline_secs(self, req: rq.Request) -> float:
        ms = req.deadline_ms or self.config.deadline_ms
        return ms / 1e3 if ms > 0 else 0.0

    def expired(self, req: rq.Request, now: float) -> bool:
        dl = self._deadline_secs(req)
        return bool(dl) and (now - req.submit_ts) > dl

    def speculative_budget(self, req: rq.Request, k: int) -> int:
        """How many draft tokens a verify step may propose for ``req``:
        ``k`` capped by (a) the emit budget — a verify step always emits
        at least one non-speculative token, so only ``max_new - emitted
        - 1`` drafts can ever be kept — and (b) the model window, so the
        speculative write extent ``[length, length + n_p]`` never leaves
        the admission-reserved block coverage. Proposing past either cap
        is verify compute that can never commit."""
        remaining = req.max_new_tokens - len(req.tokens)
        window = self.max_len - req.length - 1
        return max(0, min(int(k), remaining - 1, window))

    @property
    def pending(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self.slots)

    def running(self) -> List[Tuple[int, rq.Request]]:
        return [(i, r) for i, r in enumerate(self.slots) if r is not None]

    def gauges(self) -> dict:
        """Instantaneous load gauges — the health signals the router (and
        the per-step ``serving`` telemetry events) consume, so no caller
        ever needs to reach into queue/slot internals."""
        return {
            "queue_depth": len(self.queue),
            "queue_capacity": int(self.config.max_queue_depth),
            "slots_busy": sum(1 for r in self.slots if r is not None),
            "slots_total": len(self.slots),
            "committed_tokens": self.committed_tokens,
        }

    # ------------------------------------------------------------------
    def submit(self, req: rq.Request, now: Optional[float] = None) -> bool:
        """Queue a request, or shed it (state ``shed`` + reason) when
        admission control rejects. Returns True when queued."""
        now = self.clock() if now is None else now
        req.submit_ts = now
        self.stats["submitted"] += 1
        if req.max_new_tokens <= 0:
            req.max_new_tokens = self.config.default_max_new_tokens
        reason = self._admit_sampling(req)
        if reason is not None:
            return self._shed(req, reason, now)
        if req.request_id in self._live_ids:
            # a duplicate id would collide in the block manager mid-admit
            # and crash the serving loop with every other request in
            # flight — reject it at the door instead
            return self._shed(req, "duplicate_id", now)
        if (req.prompt_len < 1
                or bucket_for(req.prompt_len, self.buckets) is None
                or self._cost(req) > self.max_len
                # a request the POOL can never hold (explicit small
                # num_blocks) must shed now: admit() defers on allocation
                # pressure, and waiting on frees that cannot suffice
                # would spin step()/drain() forever
                or self.blocks.blocks_needed(self._cost(req))
                > self.blocks.num_blocks - 1):
            return self._shed(req, "too_long", now)
        if len(self.queue) >= self.config.max_queue_depth:
            return self._shed(req, "queue_full", now)
        cap = self.config.max_inflight_tokens
        if (cap and self.config.shed_policy != QUEUE
                and self.committed_tokens + self._cost(req) > cap):
            return self._shed(req, "inflight_tokens", now)
        self.committed_tokens += self._cost(req)
        self._live_ids.add(req.request_id)
        self.queue.append(req)
        self.stats["queue_peak"] = max(self.stats["queue_peak"],
                                       len(self.queue))
        return True

    def _admit_sampling(self, req: rq.Request) -> Optional[str]:
        """Admission control for per-request sampling: resolve the
        ``serving.sampling`` defaults onto the request (so exports,
        replays and records all carry the EFFECTIVE knobs) and return a
        shed reason when the request cannot be served reproducibly —
        ``sampling_unsupported`` (no sampling block on this engine),
        ``sampling_unseeded`` (do_sample without a seed is unreplayable
        by construction — the loud shed, never a silent greedy
        downgrade), or ``sampling_invalid`` (out-of-range knobs)."""
        if not getattr(req, "do_sample", False):
            return None
        sc = getattr(self.config, "sampling", None)
        if sc is None or not sc.enabled:
            return "sampling_unsupported"
        if req.seed is None:
            return "sampling_unseeded"
        if req.temperature is None:
            req.temperature = sc.default_temperature
        if req.top_k is None:
            req.top_k = sc.default_top_k
        if req.top_p is None:
            req.top_p = sc.default_top_p
        if (req.seed < 0 or req.temperature <= 0 or req.top_k < 0
                or not 0.0 <= req.top_p <= 1.0):
            return "sampling_invalid"
        return None

    def _shed(self, req: rq.Request, reason: str,
              now: Optional[float] = None) -> bool:
        req.state = rq.SHED
        req.finish_reason = reason
        # the caller's `now` keeps one timebase per event: under a fake
        # clock a shed record must not mix injected submit/admit times
        # with real clock reads
        req.finish_ts = self.clock() if now is None else now
        self.stats["shed"] += 1
        reasons = self.stats["shed_reasons"]
        reasons[reason] = reasons.get(reason, 0) + 1
        if self.tracer.enabled and req.trace is not None:
            # terminal shed span in the request's trace (submit-time
            # sheds carry no trace context yet and are skipped — the
            # router records those on the client handle). A pre-admission
            # shed has no serve root yet: fall back to the router-stamped
            # attempt parent so the span stays attached to its subtree
            # (a parentless shed would masquerade as the trace root)
            self.tracer.record_span(
                "shed", req.trace["trace"], to_ns(req.submit_ts),
                to_ns(req.finish_ts),
                parent=req.trace.get("serve_id") or req.trace.get("parent"),
                reason=reason, request_id=req.request_id)
        return False

    # ------------------------------------------------------------------
    def admit(self, now: Optional[float] = None):
        """Splice queued requests into free decode slots. Returns
        ``(admitted, shed)``: admitted as ``(slot, request, block_table)``
        triples (the engine prefills them), shed as requests dropped at
        the queue head (deadline already blown — prefilling them would
        burn a compile-warm slot on undeliverable work)."""
        now = self.clock() if now is None else now
        admitted, shed = [], []
        cap = self.config.max_inflight_tokens
        for slot in range(len(self.slots)):
            if self.slots[slot] is not None:
                continue
            req = None
            while self.queue:
                head = self.queue.popleft()
                if self.expired(head, now):
                    self.committed_tokens -= self._cost(head)
                    self._live_ids.discard(head.request_id)
                    self._shed(head, "deadline", now)
                    shed.append(head)
                    continue
                req = head
                break
            if req is None:
                break
            if cap and self.config.shed_policy == QUEUE:
                running_tokens = sum(self._cost(r) for _, r in
                                     self.running()) + sum(
                    self._cost(r) for _, r, _ in admitted)
                if running_tokens + self._cost(req) > cap:
                    self.queue.appendleft(req)  # defer, keep FIFO order
                    break
            shared, cow_src, matched = [], None, 0
            if self.prefix is not None:
                shared, cow_src, matched = self.prefix.match(req.prompt)
            if not self.blocks.can_allocate_shared(self._cost(req), shared,
                                                   cow_src):
                self.queue.appendleft(req)  # pool backpressure: wait
                break
            table = self.blocks.allocate(req.request_id, self._cost(req),
                                         shared=shared, cow_src=cow_src)
            req.prefix_hit_tokens = matched
            req.blocks_shared = len(shared) + (1 if cow_src is not None
                                               else 0)
            req.cached_len = matched
            # the engine copies cow_src's rows into the first fresh block
            # (logical index len(shared)) before any append, then calls
            # blocks.cow_done() to unpin the source
            req.cow = ((int(cow_src), int(table[len(shared)]))
                       if cow_src is not None else None)
            req.state = rq.RUNNING
            req.slot = slot
            req.admit_ts = now
            self.slots[slot] = req
            self.stats["admitted"] += 1
            if self.tracer.enabled:
                self._trace_admit(req, now, slot)
            admitted.append((slot, req, table))
        return admitted, shed

    def _trace_admit(self, req: rq.Request, now: float, slot: int):
        """Admission is where a request's replica-side trace context is
        ESTABLISHED: reuse the router-stamped context (same trace id,
        parent = the current attempt span) or mint a fresh trace for a
        standalone submit, open the `serve` root span (ended by the
        engine at finish/shed), and emit the submit->slot `queue` leg."""
        if req.trace is None:
            req.trace = {"trace": self.tracer.new_trace(
                hint=req.request_id)}
        if "serve_id" not in req.trace:
            h = self.tracer.begin(
                "serve", req.trace["trace"], parent=req.trace.get("parent"),
                start_ns=to_ns(req.submit_ts), request_id=req.request_id,
                attempt=req.trace.get("attempt", 0))
            req.trace["serve"] = h
            req.trace["serve_id"] = h.span
        self.tracer.record_span(
            "queue", req.trace["trace"], to_ns(req.submit_ts), to_ns(now),
            parent=req.trace.get("serve_id"), slot=slot,
            request_id=req.request_id)

    # ------------------------------------------------------------------
    def cancel(self, request_id: str, reason: str = "cancelled",
               now: Optional[float] = None) -> Optional[rq.Request]:
        """Abandon one in-flight request (queued or mid-decode), releasing
        its slot, blocks and token budget immediately; the request is
        marked shed with ``reason``. Returns it, or ``None`` when no live
        request carries the id. The multi-replica router uses this at
        failover so an abandoned proxy never haunts a replica that later
        recovers through a half-open probe."""
        now = self.clock() if now is None else now
        for i, r in enumerate(self.queue):
            if r.request_id == request_id:
                del self.queue[i]
                self.committed_tokens -= self._cost(r)
                self._live_ids.discard(request_id)
                self._shed(r, reason, now)
                return r
        for slot, r in self.running():
            if r.request_id == request_id:
                self.slots[slot] = None
                self.blocks.release(request_id)
                self.committed_tokens -= self._cost(r)
                self._live_ids.discard(request_id)
                self._shed(r, reason, now)
                return r
        return None

    # ------------------------------------------------------------------
    # live KV-block migration seams (serving/migration.py)
    def free_slot(self) -> Optional[int]:
        """Lowest free decode slot index, or None when all are busy —
        the import-side capacity probe (a migrated-in request bypasses
        the queue: it is already mid-decode, so it needs a slot NOW or
        the migration does not happen)."""
        for slot, r in enumerate(self.slots):
            if r is None:
                return slot
        return None

    def splice(self, req: rq.Request, slot: int,
               now: Optional[float] = None):
        """Register a migrated-in request directly into a free decode
        slot, mid-stream: no queue pass, no prefill — its KV blocks were
        already scattered into the pool and its table allocated by the
        engine's import path. Mirrors admission's accounting (committed
        tokens, live ids) so finish/cancel/migrate-out release exactly
        what admission-or-splice reserved."""
        now = self.clock() if now is None else now
        if self.slots[slot] is not None:
            raise ValueError(f"splice into busy slot {slot}")
        if req.request_id in self._live_ids:
            raise ValueError(f"splice of live id {req.request_id!r}")
        if req.max_new_tokens <= 0:
            req.max_new_tokens = self.config.default_max_new_tokens
        self.committed_tokens += self._cost(req)
        self._live_ids.add(req.request_id)
        req.state = rq.RUNNING
        req.slot = slot
        req.admit_ts = now
        self.slots[slot] = req
        self.stats["migrated_in"] += 1
        if self.tracer.enabled:
            # continue the request's ONE trace on this replica: a fresh
            # `serve` root under the router-stamped parent (the queue leg
            # is skipped — a spliced request never queued here)
            if req.trace is None:
                req.trace = {"trace": self.tracer.new_trace(
                    hint=req.request_id)}
            if "serve_id" not in req.trace:
                h = self.tracer.begin(
                    "serve", req.trace["trace"],
                    parent=req.trace.get("parent"), start_ns=to_ns(now),
                    request_id=req.request_id,
                    attempt=req.trace.get("attempt", 0))
                req.trace["serve"] = h
                req.trace["serve_id"] = h.span

    def migrate_out(self, request_id: str,
                    now: Optional[float] = None) -> Optional[rq.Request]:
        """Release a RUNNING request's slot + blocks + token budget after
        its state committed on a migration target. NOT a shed (no shed
        stats, no shed span — the request lives on, elsewhere) and not a
        finish: the terminal state here is ``shed``/``migrated`` purely
        so the abandoned source proxy reads as done to anything still
        holding it. Returns the request, or None when the id is not
        running here (queued requests migrate by plain resubmit)."""
        now = self.clock() if now is None else now
        for slot, r in self.running():
            if r.request_id == request_id:
                self.slots[slot] = None
                self.blocks.release(request_id)
                self.committed_tokens -= self._cost(r)
                self._live_ids.discard(request_id)
                r.state = rq.SHED
                r.finish_reason = "migrated"
                r.finish_ts = now
                self.stats["migrated_out"] += 1
                return r
        return None

    def finish(self, req: rq.Request, reason: str,
               now: Optional[float] = None):
        """Release a running request's slot + blocks + token budget."""
        now = self.clock() if now is None else now
        if req.slot >= 0 and self.slots[req.slot] is req:
            self.slots[req.slot] = None
        self.blocks.release(req.request_id)
        self.committed_tokens -= self._cost(req)
        self._live_ids.discard(req.request_id)
        req.state = rq.FINISHED
        req.finish_reason = reason
        req.finish_ts = now
        self.stats["finished"] += 1
