"""SLO error-budget autoscaler policy for the replica fleet.

Pure host-side policy (no jax, no clock reads — every timestamp and
step count is injected, so the trace-replay harness drives it
deterministically under fake clocks). The policy half of the fleet
manager: :class:`~deepspeed_tpu.serving.router.FleetManager` feeds it
per-step evidence and executes whatever it decides through the router's
``start_drain``/``reactivate`` seams.

The SRE framing, concretely:

- **error budgets** — each SLO target defines an *allowed* failure
  rate. ``target_shed_rate`` allows that fraction of submits to shed;
  ``target_ttft_p95_ms`` allows 5% of finished requests over the target
  (that IS the p95 semantic, read as a budget).
- **burn rate** — observed failure rate over allowed rate, per sliding
  step window. Burn 1.0 = spending the budget exactly as fast as it
  refills; 2.0 = the budget is gone in half the window.
- **two windows** — a short *fast* window catches an overload spike
  early (scale up on ``burn_rate_fast``); a long *slow* window is the
  budget-remaining accounting and the scale-down quiet gate (you only
  shrink a fleet whose long-horizon budget is intact).
- **hysteresis + cooldowns** — scale-up is eager (one cooldown);
  scale-down needs ``scale_down_quiet_steps`` *consecutive* quiet steps
  (low load and fast burns within budget) plus its own cooldown, so a
  diurnal shoulder never flaps the fleet.

Queue pressure (the router's overload score) is a leading indicator
that triggers growth before any budget actually burns —
``scale_up_load`` — and gates shrinking — ``scale_down_load``.
"""

import dataclasses
from collections import deque
from typing import Dict, Iterable, Optional

from deepspeed_tpu.serving.config import FleetConfig

SCALE_UP = "up"
SCALE_DOWN = "down"

# the p95 semantic as an error budget: 5% of requests may exceed the
# p95 target before the budget burns at exactly rate 1.0
TTFT_P95_ALLOWED = 0.05


class BudgetWindow:
    """One SLO error budget over a sliding window of per-step samples.

    Each step contributes ``(good, bad)`` counts; the burn rate is the
    window's bad fraction over the allowed fraction. Steps with no
    traffic contribute nothing (an idle fleet neither burns nor refills
    evidence)."""

    def __init__(self, window_steps: int, allowed_rate: float):
        self.window = deque(maxlen=int(window_steps))
        self.allowed = float(allowed_rate)

    def observe(self, good: int, bad: int) -> None:
        self.window.append((int(good), int(bad)))

    @property
    def rate(self) -> Optional[float]:
        good = sum(g for g, _ in self.window)
        bad = sum(b for _, b in self.window)
        total = good + bad
        return bad / total if total else None

    def burn_rate(self) -> Optional[float]:
        """Observed/allowed failure rate (None with no samples). An
        allowed rate of zero makes any failure an infinite burn — the
        strictest budget, not a crash."""
        rate = self.rate
        if rate is None:
            return None
        if self.allowed <= 0:
            return float("inf") if rate > 0 else 0.0
        return rate / self.allowed

    def remaining(self) -> Optional[float]:
        """Fraction of the window's budget left (clamped at 0)."""
        burn = self.burn_rate()
        if burn is None:
            return None
        return max(0.0, round(1.0 - burn, 4))


@dataclasses.dataclass
class Decision:
    action: str            # SCALE_UP | SCALE_DOWN
    reason: str            # "ttft_burn" | "shed_burn" | "load" | "quiet"
    step: int
    burn: Optional[float] = None
    overload: float = 0.0


class Autoscaler:
    """The decision policy. Call :meth:`observe_requests` with every
    terminal request record (finished AND shed — submit-time sheds
    included), :meth:`observe_step` once per router step, then
    :meth:`decide`. Stateless about the fleet itself: the caller says
    what the current size and bounds allow."""

    def __init__(self, config: FleetConfig):
        if isinstance(config, dict):
            config = FleetConfig(**config)
        self.config: FleetConfig = config
        c = config
        # fast windows drive scale-up; slow windows gate scale-down and
        # report budget remaining
        self._ttft_fast = BudgetWindow(c.fast_window_steps,
                                       TTFT_P95_ALLOWED)
        self._ttft_slow = BudgetWindow(c.slow_window_steps,
                                       TTFT_P95_ALLOWED)
        self._shed_fast = BudgetWindow(c.fast_window_steps,
                                       c.target_shed_rate)
        self._shed_slow = BudgetWindow(c.slow_window_steps,
                                       c.target_shed_rate)
        # per-step accumulators, flushed into the windows at observe_step
        self._ttft_pending = [0, 0]    # good, over-target
        self._shed_pending = [0, 0]    # finished, shed
        self._quiet_steps = 0
        self._last_scale_step: Optional[int] = None
        self._last_overload = 0.0
        self._step = 0

    # ------------------------------------------------------------------
    # evidence
    def observe_requests(self, records: Iterable[dict]) -> None:
        """Feed terminal request records (``RouterRequest.record()`` /
        ``Request.record()`` payloads: ``state``, ``ttft_ms``)."""
        c = self.config
        for r in records:
            if r.get("state") == "shed":
                self._shed_pending[1] += 1
                continue
            self._shed_pending[0] += 1
            ttft = r.get("ttft_ms")
            if c.target_ttft_p95_ms > 0 and ttft is not None:
                over = float(ttft) > c.target_ttft_p95_ms
                self._ttft_pending[1 if over else 0] += 1

    def observe_step(self, overload: float) -> None:
        """Close the step: flush pending request evidence into the burn
        windows and advance the quiet streak."""
        self._step += 1
        for w in (self._ttft_fast, self._ttft_slow):
            w.observe(*self._ttft_pending)
        for w in (self._shed_fast, self._shed_slow):
            w.observe(*self._shed_pending)
        self._ttft_pending = [0, 0]
        self._shed_pending = [0, 0]
        self._last_overload = float(overload)
        if (overload <= self.config.scale_down_load
                and not self._burning(fast=True)):
            self._quiet_steps += 1
        else:
            self._quiet_steps = 0

    # ------------------------------------------------------------------
    # policy
    def _burns(self, fast: bool) -> Dict[str, Optional[float]]:
        c = self.config
        out = {}
        if c.target_ttft_p95_ms > 0:
            out["ttft"] = (self._ttft_fast if fast
                           else self._ttft_slow).burn_rate()
        if c.target_shed_rate > 0:
            out["shed"] = (self._shed_fast if fast
                           else self._shed_slow).burn_rate()
        return out

    def _burning(self, fast: bool) -> bool:
        thr = self.config.burn_rate_fast if fast else 1.0
        return any(b is not None and b >= thr
                   for b in self._burns(fast).values())

    def _cooled(self, steps: int) -> bool:
        return (self._last_scale_step is None
                or self._step - self._last_scale_step >= steps)

    def decide(self, active: int, *, can_grow: bool = True,
               can_shrink: bool = True,
               overload: Optional[float] = None) -> Optional[Decision]:
        """One decision per call (the fleet manager calls once per
        step, after :meth:`observe_step`). ``overload`` defaults to the
        value the last :meth:`observe_step` saw."""
        c = self.config
        if overload is None:
            overload = self._last_overload
        if can_grow and active < c.max_replicas \
                and self._cooled(c.scale_up_cooldown_steps):
            burns = self._burns(fast=True)
            hot = [(k, b) for k, b in burns.items()
                   if b is not None and b >= c.burn_rate_fast]
            if hot:
                name, burn = max(hot, key=lambda kv: kv[1])
                return self._mark(Decision(SCALE_UP, f"{name}_burn",
                                           self._step, burn=burn,
                                           overload=overload))
            if overload >= c.scale_up_load:
                return self._mark(Decision(SCALE_UP, "load", self._step,
                                           overload=overload))
        if can_shrink and active > c.min_replicas \
                and self._quiet_steps >= c.scale_down_quiet_steps \
                and self._cooled(c.scale_down_cooldown_steps) \
                and not self._burning(fast=False):
            return self._mark(Decision(SCALE_DOWN, "quiet", self._step,
                                       overload=overload))
        return None

    def _mark(self, decision: Decision) -> Decision:
        self._last_scale_step = self._step
        self._quiet_steps = 0
        return decision

    # ------------------------------------------------------------------
    def budget_remaining(self) -> Dict[str, Optional[float]]:
        """Slow-window budget remaining per enabled SLO (the number the
        fleet gauge event and ``FleetManager.stats()`` surface)."""
        c = self.config
        out: Dict[str, Optional[float]] = {}
        if c.target_ttft_p95_ms > 0:
            out["ttft"] = self._ttft_slow.remaining()
        if c.target_shed_rate > 0:
            out["shed"] = self._shed_slow.remaining()
        return out

    def burn_rates(self) -> Dict[str, Dict[str, Optional[float]]]:
        """Fast- and slow-window burn rates per enabled SLO —
        ``{"ttft": {"fast": ..., "slow": ...}, ...}`` (values None with
        no window evidence). The internal numbers the scale-up policy
        acts on, made externally visible: the metrics plane exports
        them as ``ds_slo_burn_rate{slo,window}`` gauges."""
        fast, slow = self._burns(fast=True), self._burns(fast=False)
        return {name: {"fast": fast.get(name), "slow": slow.get(name)}
                for name in fast}
