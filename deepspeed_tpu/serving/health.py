"""Per-replica health: a state machine over signals the telemetry
stream already carries.

Pure host-side policy (no jax imports — same contract as the
scheduler): the router holds one :class:`ReplicaHealth` per replica and
feeds it exactly four kinds of evidence, none of which require reaching
into the replica's internals:

- **step outcomes** — an exception from ``submit()``/``step()`` is a
  failure; a clean step is a success (resets the consecutive-failure
  count);
- **stall verdicts** — host-observed step wall time past the configured
  timeout (the hang-watchdog signal at router granularity) trips the
  breaker immediately: a wedged collective does not get
  ``failure_threshold`` chances;
- **crash verdicts** — an exception whose ``replica_dead`` attribute is
  true (e.g. :class:`~deepspeed_tpu.runtime.resilience.chaos.
  ReplicaCrashed`) is unrecoverable: the replica goes ``DEAD`` and never
  comes back without an explicit :meth:`reactivate`;
- **telemetry aggregates** — TTFT p95 / shed rate from the replica's own
  ``stats()`` window soft-degrade a replica (still routable, but only
  after every HEALTHY peer), with hysteresis so a borderline replica
  does not flap.

States::

    HEALTHY <-> DEGRADED          (soft telemetry signals, hysteresis)
       |            |
       +--- trip ---+---> TRIPPED ---(backoff elapses)---> half-open probe
                            |  ^                               |
                            |  +------- probe failed ----------+
                            |  (backoff doubles: retry_io's series)
                            +--> DEAD  (crash, or > max_trips)

    DRAINING                      (rolling restart: no new work, in-flight
                                   finishes; reactivate() -> HEALTHY)

The breaker's half-open schedule is the same exponential series the
PR 3 checkpoint retry helper walks (``retry_io``: ``base * 2**(n-1)``)
— :func:`probe_backoff` is that formula, named.
"""

import time
from typing import Callable, Optional

HEALTHY = "healthy"
DEGRADED = "degraded"
TRIPPED = "tripped"
DEAD = "dead"
DRAINING = "draining"

STATES = (HEALTHY, DEGRADED, TRIPPED, DEAD, DRAINING)


def probe_backoff(base_secs: float, trips: int) -> float:
    """Half-open probe delay after the ``trips``-th breaker trip — the
    ``retry_io`` exponential series (``base * 2**(trips-1)``)."""
    return float(base_secs) * (2 ** max(0, int(trips) - 1))


class ReplicaHealth:
    def __init__(self, config, replica_id: int = 0, clock=time.monotonic,
                 emit: Optional[Callable] = None):
        self.config = config
        self.replica_id = int(replica_id)
        self.clock = clock
        self._emit = emit
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.trips = 0            # lifetime breaker trips (stats; a probe
        #                           close does NOT erase the history)
        self.trip_streak = 0      # trips since the last close — drives the
        #                           backoff series and the DEAD gate
        self.next_probe_ts = 0.0  # earliest half-open probe after a trip
        self.probing = False      # a half-open probe request is in flight
        self.last_reason: Optional[str] = None

    # ------------------------------------------------------------------
    def _set_state(self, new: str, reason: str):
        if new == self.state:
            return
        old, self.state, self.last_reason = self.state, new, reason
        if self._emit is not None:
            self._emit("replica.state", replica=self.replica_id,
                       from_state=old, to_state=new, reason=reason)

    @property
    def routable(self) -> bool:
        """May receive regular traffic (probes are separate: a TRIPPED
        replica takes exactly one request once its backoff elapses)."""
        return self.state in (HEALTHY, DEGRADED)

    @property
    def alive(self) -> bool:
        return self.state != DEAD

    # ------------------------------------------------------------------
    # breaker / hard signals
    def can_probe(self, now: float) -> bool:
        return (self.state == TRIPPED and not self.probing
                and now >= self.next_probe_ts)

    def begin_probe(self):
        self.probing = True
        if self._emit is not None:
            self._emit("breaker.probe", replica=self.replica_id,
                       trips=self.trips)

    def probe_success(self):
        """The half-open probe request finished: close the breaker and
        reset the backoff series (a recovered replica starts clean —
        but ``trips`` keeps the lifetime count for stats)."""
        self.probing = False
        self.trip_streak = 0
        self.consecutive_failures = 0
        self._set_state(HEALTHY, "probe_success")
        if self._emit is not None:
            self._emit("breaker.close", replica=self.replica_id)

    def probe_inconclusive(self):
        """The probe request was shed by replica-side admission policy
        (deadline, queue) — no verdict either way; allow another probe."""
        self.probing = False

    def record_success(self):
        self.consecutive_failures = 0

    def record_failure(self, reason: str = "failure"):
        if self.state == DEAD:
            return
        self.consecutive_failures += 1
        if self.probing or (self.consecutive_failures
                            >= self.config.failure_threshold):
            self.trip(reason)

    def record_stall(self, reason: str = "stall"):
        """A stall verdict is definitive — trip now, don't count to
        ``failure_threshold`` while requests sit behind a wedged step."""
        self.trip(reason)

    def record_crash(self, reason: str = "crash"):
        self.probing = False
        self._set_state(DEAD, reason)

    def trip(self, reason: str):
        if self.state in (DEAD, DRAINING):
            return
        self.probing = False
        self.consecutive_failures = 0
        self.trips += 1
        self.trip_streak += 1
        # dedicated event: a re-trip while already TRIPPED (failed
        # half-open probe) changes no state, so state-change events
        # alone undercount breaker activity
        if self._emit is not None:
            self._emit("breaker.trip", replica=self.replica_id,
                       trips=self.trips, reason=reason)
        if self.trip_streak > self.config.max_trips:
            self._set_state(DEAD, f"max_trips:{reason}")
            return
        self.next_probe_ts = self.clock() + probe_backoff(
            self.config.probe_backoff_secs, self.trip_streak)
        self._set_state(TRIPPED, reason)

    # ------------------------------------------------------------------
    # soft signals (telemetry aggregates), with hysteresis
    def observe(self, ttft_p95_ms=None, shed_rate=None):
        if self.state not in (HEALTHY, DEGRADED):
            return
        c = self.config
        checks = []
        if c.degraded_ttft_ms > 0 and ttft_p95_ms is not None:
            checks.append((float(ttft_p95_ms), c.degraded_ttft_ms))
        if c.degraded_shed_rate > 0 and shed_rate is not None:
            checks.append((float(shed_rate), c.degraded_shed_rate))
        if not checks:
            return
        if any(v > thr for v, thr in checks):
            self._set_state(DEGRADED, "telemetry")
        elif self.state == DEGRADED and all(
                v <= thr * c.degraded_exit_fraction for v, thr in checks):
            self._set_state(HEALTHY, "recovered")

    # ------------------------------------------------------------------
    # rolling restarts
    def start_drain(self):
        """No-op on an already-DRAINING replica (a repeated drain call —
        an operator retry, or the fleet manager re-evaluating — must not
        reset drain bookkeeping or cancel an open probe verdict), and on
        a DEAD one (there is nothing left to drain; ``reactivate`` is
        the only door back)."""
        if self.state in (DRAINING, DEAD):
            return
        self.probing = False
        self._set_state(DRAINING, "drain")

    def reactivate(self):
        """The drained/restarted replica is back: clean slate (an
        explicit operator action — lifetime count included)."""
        self.consecutive_failures = 0
        self.trips = 0
        self.trip_streak = 0
        self.probing = False
        self._set_state(HEALTHY, "reactivate")
