"""Speculative-decoding proposers: host-side draft-token sources.

Draft-and-verify decoding splits each decode step in two: a cheap
*proposer* guesses up to ``k`` continuation tokens on the host, then ONE
compiled verify program scores all ``k`` guesses (plus the pending last
token) in a single dispatch and the engine keeps the longest prefix the
target model agrees with. Greedy decode is bit-reproducible in this
stack (PR 4, re-proven across failover in PR 6), so "agrees with" is an
exact token comparison — the accepted stream is *identical* to
non-speculative decode, only cheaper: every verify step emits between 1
and ``k + 1`` tokens for the dispatch cost of one.

This module is the proposer side only and is host-only by contract (no
jax imports — GL01-pinned, same registry as the scheduler/blocks tier):
proposing is list-of-int work the step loop does between dispatches.
Two built-ins:

- :class:`PromptLookupProposer` — prompt-lookup / n-gram matching
  (assisted generation without a draft model): the request's own
  context (prompt + tokens generated so far) is searched for the most
  recent earlier occurrence of its current suffix n-gram, and the
  tokens that followed that occurrence are proposed. Free at serve
  time, and very effective on extractive/repetitive generations
  (summarization, code completion, greedy repetition loops).
- :class:`DraftModelProposer` — a small draft model proposes the next
  ``k`` tokens greedily. The draft is injected as a callable or an
  engine-like object (``ServingEngine(..., draft_model=...)``) — this
  module never constructs device programs, so the policy tier stays
  jax-free.

The verify side (the ``serving.verify[slots=N,k=K]`` program, KV
commit/drop through the block manager's speculative ledger) lives in
:mod:`deepspeed_tpu.serving.engine`.
"""

from typing import Callable, List, Optional, Sequence

from deepspeed_tpu.serving import request as rq


class Proposer:
    """One host-side draft-token source. ``propose`` returns up to ``k``
    guessed continuation tokens for the request's current context (its
    prompt plus every token generated so far); fewer (or none) is
    always legal — the engine right-pads the verify batch against the
    garbage block, so a short proposal costs nothing extra."""

    name = "null"

    def propose(self, req: rq.Request, k: int) -> List[int]:
        raise NotImplementedError

    @staticmethod
    def context(req: rq.Request) -> List[int]:
        """The request's full generated-so-far context, as plain ints.
        The serving engine already normalizes ``prompt`` to a list of
        ints at submit (and ``emit_token`` appends ints), so the common
        case is two list concats — no per-token conversion in the
        per-step host hot loop; only a raw array prompt (direct
        scheduler use) pays the conversion."""
        p = req.prompt
        if type(p) is not list:
            p = [int(t) for t in p]
        return p + req.tokens


class PromptLookupProposer(Proposer):
    """Prompt-lookup (n-gram) proposer: match the context's trailing
    n-gram against its own earlier occurrences and propose what followed
    the most recent one.

    Longest n-grams are tried first (``max_ngram`` down to
    ``min_ngram``) — a longer match is stronger evidence the
    continuation repeats — and within one n-gram size the most RECENT
    earlier occurrence wins (recent repetition predicts the near future
    better than a stale one). No match proposes nothing, which the
    engine treats as a plain decode step for that slot.

    ``window`` bounds the scan to the trailing tokens (``0`` =
    unbounded): the scan is pure-Python host work on the step-critical
    path and a MISS pays the whole scan every step, so long-context
    serving needs the bound (recent context is also where predictive
    repetition lives).
    """

    name = "prompt_lookup"

    def __init__(self, min_ngram: int = 1, max_ngram: int = 3,
                 window: int = 0):
        if not (1 <= int(min_ngram) <= int(max_ngram)):
            raise ValueError(
                f"prompt lookup needs 1 <= min_ngram <= max_ngram, got "
                f"min={min_ngram} max={max_ngram}")
        if int(window) < 0:
            raise ValueError(f"prompt lookup window must be >= 0 "
                             f"(0 = unbounded), got {window}")
        self.min_ngram = int(min_ngram)
        self.max_ngram = int(max_ngram)
        self.window = int(window)

    def propose(self, req: rq.Request, k: int) -> List[int]:
        ctx = self.context(req)
        k = int(k)
        if k <= 0 or len(ctx) < self.min_ngram + 1:
            return []
        floor = max(0, len(ctx) - self.window) if self.window else 0
        for n in range(min(self.max_ngram, len(ctx) - 1),
                       self.min_ngram - 1, -1):
            suffix = ctx[-n:]
            # scan right-to-left (recent repetition predicts best),
            # excluding the suffix's own position — but a match hard
            # against the context tail yields a TRUNCATED continuation
            # (a period-1 loop's most recent match proposes one token),
            # so keep scanning for the nearest match with a full
            # k-token continuation and fall back to the longest short
            # one only when none exists
            best: List[int] = []
            for i in range(len(ctx) - n - 1, floor - 1, -1):
                if ctx[i:i + n] == suffix:
                    cont = ctx[i + n:i + n + k]
                    if len(cont) >= k:
                        return cont
                    if len(cont) > len(best):
                        best = cont
            if best:
                return best
        return []


class DraftModelProposer(Proposer):
    """Draft-model proposer: a small model guesses the next ``k`` tokens
    greedily from the request's trailing context.

    ``draft`` is either a plain callable ``(context_tokens, k) ->
    sequence of proposed tokens`` or an engine-like object exposing
    ``generate(ids, max_new_tokens=, do_sample=)`` over a ``[1, T]``
    batch (an :class:`~deepspeed_tpu.inference.engine.InferenceEngine`
    on a shrunk config fits as-is). ``context_window`` bounds how much
    trailing context the draft sees per step (``0`` = all of it) — the
    draft runs every decode step, so its per-call cost is the knob that
    decides whether speculation pays.
    """

    name = "draft_model"

    def __init__(self, draft, context_window: int = 0):
        if draft is None:
            raise ValueError(
                'proposer "draft_model" needs a draft: pass draft_model= '
                "to ServingEngine/init_serving (a callable "
                "(context, k) -> tokens, or an engine with .generate)")
        self.context_window = int(context_window)
        generate = getattr(draft, "generate", None)
        if callable(draft) and generate is None:
            self._fn: Callable = draft
        elif callable(generate):
            self._fn = self._wrap_generate(generate)
        else:
            raise ValueError(
                f"draft_model must be callable or expose .generate, got "
                f"{type(draft).__name__}")

    @staticmethod
    def _wrap_generate(generate) -> Callable:
        def fn(ctx: Sequence[int], k: int):
            out = generate([list(ctx)], max_new_tokens=int(k),
                           do_sample=False)
            # [1, T + k] -> the k generated tail tokens
            return list(out[0])[len(ctx):]

        return fn

    def propose(self, req: rq.Request, k: int) -> List[int]:
        ctx = self.context(req)
        if self.context_window > 0:
            ctx = ctx[-self.context_window:]
        if int(k) <= 0 or not ctx:
            return []
        out = self._fn(ctx, int(k))
        return [int(t) for t in out][:int(k)]


def build_proposer(spec_cfg, draft_model=None) -> Optional[Proposer]:
    """The engine-facing factory: a :class:`Proposer` for one
    ``serving.speculative`` block, or ``None`` when the block is absent
    or disabled (speculation does not exist; the decode program and its
    step loop are exactly as before)."""
    if spec_cfg is None or not spec_cfg.enabled:
        return None
    if spec_cfg.proposer == "prompt_lookup":
        return PromptLookupProposer(
            min_ngram=spec_cfg.prompt_lookup_min_ngram,
            max_ngram=spec_cfg.prompt_lookup_max_ngram,
            window=spec_cfg.prompt_lookup_window)
    if spec_cfg.proposer == "draft_model":
        return DraftModelProposer(
            draft_model, context_window=spec_cfg.draft_context_window)
    raise ValueError(
        f"unknown speculative proposer {spec_cfg.proposer!r} "
        '(known: "prompt_lookup", "draft_model")')
