"""Serving layer: continuous batching over a paged KV-cache block pool.

The request-level runtime in front of the inference engines — the
TPU-native analog of the reference's kernel-injected *serving* stack
(DeepSpeed-MII / inference-v2): a shape-bucketed continuous-batching
scheduler over sharded executables, built on the Pallas
``decode_attention`` kernels.

Pieces:

- :class:`~deepspeed_tpu.serving.blocks.BlockManager` — paged KV-cache
  accounting: fixed-size blocks, per-sequence block tables, immediate
  frees;
- :class:`~deepspeed_tpu.serving.scheduler.ContinuousBatchingScheduler`
  — admission queue -> bucketed prefill -> decode slots, with
  backpressure (queue depth / in-flight tokens / deadlines) and a
  shed-or-queue policy;
- :class:`~deepspeed_tpu.serving.engine.ServingEngine` — the device
  runtime: fixed-bucket jitted prefill + one decode-slot program, so
  steady-state retrace count is zero;
- :class:`~deepspeed_tpu.serving.request.Request` — one in-flight
  generation with streaming callbacks and per-request telemetry;
- :mod:`~deepspeed_tpu.serving.spec_decode` — speculative decoding's
  host-side proposers (prompt-lookup n-gram matching, injected draft
  models); the engine's ONE compiled k-token verify program scores the
  proposals and commits the greedy-agreed prefix (1 to k+1 tokens per
  dispatch, bit-identical streams);
- :class:`~deepspeed_tpu.serving.router.ReplicaRouter` +
  :class:`~deepspeed_tpu.serving.health.ReplicaHealth` — the resilient
  multi-replica front door: health-aware routing, failover with
  deterministic replay, and an SLO-guarded degradation ladder;
- the fleet tier — :class:`~deepspeed_tpu.serving.router.FleetManager`
  (elastic scale over the router's drain/reactivate seams, through the
  :class:`~deepspeed_tpu.serving.router.ReplicaFactory` warm-build
  seam), :mod:`~deepspeed_tpu.serving.autoscaler` (the SLO error-budget
  policy), :mod:`~deepspeed_tpu.serving.replay` (trace-driven workload
  replay over fake clocks) and
  :class:`~deepspeed_tpu.serving.capacity.CapacityModel` (latency-vs-
  load curves + ``fleet_size_for``);
- :class:`~deepspeed_tpu.serving.gateway.ServingGateway` +
  :mod:`~deepspeed_tpu.serving.tenancy` — the HTTP/SSE front door over
  any of the above: ``POST /v1/generate`` token streaming, per-tenant
  API keys, token-bucket quotas and SLO classes mapped onto the
  scheduler's priority floor, with ``/healthz`` and ``/metrics`` on the
  same port; :class:`~deepspeed_tpu.serving.replay.HttpReplayDriver`
  replays JSONL traces through it end to end.
"""

from deepspeed_tpu.serving.autoscaler import Autoscaler, BudgetWindow
from deepspeed_tpu.serving.blocks import BlockManager
from deepspeed_tpu.serving.capacity import CapacityModel
from deepspeed_tpu.serving.config import (FleetConfig, GatewayConfig,
                                          GatewayTenantConfig,
                                          MigrationConfig,
                                          ReplayConfig,
                                          RouterConfig, ServingConfig,
                                          SloClassConfig,
                                          SpeculativeConfig, bucket_for,
                                          resolve_buckets)
from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.gateway import ServingGateway
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.health import (DEAD, DEGRADED, DRAINING, HEALTHY,
                                          TRIPPED, ReplicaHealth)
from deepspeed_tpu.serving.migration import Migrator, resolve_migration
from deepspeed_tpu.serving.replay import (Arrival, HttpReplayDriver,
                                          ReplayClock,
                                          TraceReplayer, burst_trace,
                                          diurnal_trace, load_trace,
                                          save_trace, synthesize_trace)
from deepspeed_tpu.serving.tenancy import (Tenant, TenantTable,
                                           TokenBucket)
from deepspeed_tpu.serving.request import (FINISHED, QUEUED, RUNNING, SHED,
                                           Request)
from deepspeed_tpu.serving.router import (CallableReplicaFactory,
                                          FleetManager, ReplicaFactory,
                                          ReplicaRouter, RouterRequest)
from deepspeed_tpu.serving.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.serving.spec_decode import (DraftModelProposer,
                                               PromptLookupProposer,
                                               Proposer, build_proposer)

__all__ = ["Arrival", "Autoscaler", "BlockManager", "BudgetWindow",
           "CallableReplicaFactory", "CapacityModel",
           "ContinuousBatchingScheduler",
           "DraftModelProposer", "FleetConfig", "FleetManager",
           "GatewayConfig", "GatewayTenantConfig", "HttpReplayDriver",
           "MigrationConfig", "Migrator", "resolve_migration",
           "PrefixCache", "PromptLookupProposer",
           "Proposer", "ReplayClock", "ReplayConfig", "ReplicaFactory",
           "ReplicaHealth",
           "ReplicaRouter", "Request", "RouterConfig", "RouterRequest",
           "ServingConfig", "ServingEngine", "ServingGateway",
           "SloClassConfig", "SpeculativeConfig",
           "Tenant", "TenantTable", "TokenBucket",
           "TraceReplayer", "bucket_for", "build_proposer", "burst_trace",
           "diurnal_trace", "load_trace", "resolve_buckets", "save_trace",
           "synthesize_trace",
           "QUEUED", "RUNNING", "FINISHED", "SHED",
           "HEALTHY", "DEGRADED", "TRIPPED", "DEAD", "DRAINING"]
