"""Paged KV-cache block manager.

The device-side cache is a shared pool of ``num_blocks`` fixed-size
blocks per layer (``[num_blocks, block_size, H, D]``); this class owns
the host-side accounting: which pool blocks belong to which sequence,
expressed as a per-sequence *block table* (logical block j of sequence s
lives in pool block ``table[j]``). Sequences of different lengths share
the one allocation, and a finished sequence's blocks return to the free
list immediately — the next admission reuses them without touching the
device.

Block 0 is the reserved garbage sink (``GARBAGE_BLOCK``): it is never
allocated, table rows pad with it, and bucketed-prefill pad tokens (and
idle decode slots) scatter their KV writes into it.
"""

from typing import Dict, List

import numpy as np

from deepspeed_tpu.serving.config import blocks_for_tokens

# mirror of ops.decode_attention.GARBAGE_BLOCK without importing jax
GARBAGE_BLOCK = 0


class BlockManager:
    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block {GARBAGE_BLOCK} is the "
                f"reserved garbage sink), got {num_blocks}")
        if block_size <= 0 or max_blocks_per_seq <= 0:
            raise ValueError("block_size and max_blocks_per_seq must be > 0")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        # LIFO free list: recently-freed blocks are re-handed first (their
        # pool pages are the likeliest still resident)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: Dict[str, List[int]] = {}

    # ------------------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def blocks_needed(self, n_tokens: int) -> int:
        """Blocks covering ``n_tokens`` cache slots (at least one: every
        sequence owns a block from its first token) — the one shared
        block-count formula (``config.blocks_for_tokens``)."""
        return blocks_for_tokens(n_tokens, self.block_size)

    def can_allocate(self, n_blocks: int) -> bool:
        return len(self._free) >= int(n_blocks)

    # ------------------------------------------------------------------
    def allocate(self, seq_id: str, n_tokens: int) -> np.ndarray:
        """Allocate blocks covering ``n_tokens`` and return the sequence's
        ``[max_blocks_per_seq]`` int32 block table (unused tail = garbage
        block). Raises on double allocation or exhaustion — admission
        control must check :meth:`can_allocate` first."""
        if seq_id in self._owned:
            raise ValueError(f"sequence {seq_id!r} already owns blocks")
        need = self.blocks_needed(n_tokens)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"{n_tokens} tokens need {need} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq}")
        if need > len(self._free):
            raise RuntimeError(
                f"cache pool exhausted: {need} blocks needed, "
                f"{len(self._free)} free")
        blocks = [self._free.pop() for _ in range(need)]
        self._owned[seq_id] = blocks
        table = np.full((self.max_blocks_per_seq,), GARBAGE_BLOCK, np.int32)
        table[:need] = blocks
        return table

    def release(self, seq_id: str) -> int:
        """Free a finished sequence's blocks immediately; returns how many
        were freed. Unknown ids are a no-op (a shed request never owned
        blocks)."""
        blocks = self._owned.pop(seq_id, None)
        if not blocks:
            return 0
        self._free.extend(reversed(blocks))
        return len(blocks)

    def owned(self, seq_id: str) -> List[int]:
        return list(self._owned.get(seq_id, ()))
