"""Paged KV-cache block manager: refcounted pool with copy-on-write.

The device-side cache is a shared pool of ``num_blocks`` fixed-size
blocks per layer (``[num_blocks, block_size, H, D]``); this class owns
the host-side accounting: which pool blocks belong to which sequence,
expressed as a per-sequence *block table* (logical block j of sequence s
lives in pool block ``table[j]``). Sequences of different lengths share
the one allocation, and a finished sequence's blocks return to the free
list immediately — the next admission reuses them without touching the
device.

Block 0 is the reserved garbage sink (``GARBAGE_BLOCK``): it is never
allocated, table rows pad with it, and bucketed-prefill pad tokens (and
idle decode slots) scatter their KV writes into it.

Prefix sharing (the serving fast path's substrate) adds three ideas on
top of the plain free list, all invisible until a prefix cache drives
them:

- **refcounts** — a physical block may appear in several sequences'
  block tables at once (a shared system prompt prefilled exactly once).
  ``release()`` decrements instead of freeing; the block's storage is
  reclaimed only when the last holder lets go.
- **cached / evictable blocks** — a block the prefix cache indexes
  outlives its last owner: at refcount zero it parks on an LRU
  *evictable* list (its KV bytes intact, ready to be re-shared) instead
  of the free list, and is recycled lazily only when an allocation
  finds the free list empty. ``num_free`` counts both tiers — evictable
  blocks are reclaimable without touching any live sequence.
- **copy-on-write** — a partially-filled cached block can be mapped
  into a new sequence only as a private copy (appending in place would
  corrupt every other reader). ``allocate(cow_src=...)`` pins the
  source until the engine confirms the device-side copy with
  :meth:`cow_done`, so an interleaved allocation can never evict the
  source mid-copy.

Host-only by contract: no jax imports (pinned by the AST import-hygiene
test) — the scheduler/policy tier must run in milliseconds on any box.
"""

from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.serving.config import blocks_for_tokens

# mirror of ops.decode_attention.GARBAGE_BLOCK without importing jax
GARBAGE_BLOCK = 0


class BlockManager:
    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block {GARBAGE_BLOCK} is the "
                f"reserved garbage sink), got {num_blocks}")
        if block_size <= 0 or max_blocks_per_seq <= 0:
            raise ValueError("block_size and max_blocks_per_seq must be > 0")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        # LIFO free list: recently-freed blocks are re-handed first (their
        # pool pages are the likeliest still resident)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: Dict[str, List[int]] = {}
        # physical block -> number of live holders (owning sequences plus
        # at most one pending-COW pin per admitting sequence)
        self._ref: Dict[int, int] = {}
        # blocks the prefix cache indexes (their KV must stay immutable
        # and their storage outlives the owning sequence)
        self._cached = set()
        # cached blocks at refcount zero, oldest-touched first — the LRU
        # eviction ladder. Values unused; OrderedDict for move_to_end.
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        # seq -> pinned COW source block (held until cow_done/release)
        self._cow_pending: Dict[str, int] = {}
        # seq -> owned-block count BEFORE its open speculative window
        # (speculate() grants extra blocks past it; commit/drop return
        # the uncommitted tail to the free list without copies)
        self._spec_base: Dict[str, int] = {}
        # notification hook: called with the block id when an evictable
        # block is recycled, so the prefix cache can drop its trie entry
        self.on_evict = None
        self.evictions = 0

    # ------------------------------------------------------------------
    @property
    def num_free(self) -> int:
        """Blocks an allocation can claim without touching a live
        sequence: the free list plus the evictable (cached, refcount-0)
        tier."""
        return len(self._free) + len(self._evictable)

    @property
    def num_allocated(self) -> int:
        return (self.num_blocks - 1) - self.num_free

    @property
    def num_cached(self) -> int:
        """Blocks the prefix cache currently indexes (live or
        evictable)."""
        return len(self._cached)

    def ref_count(self, block: int) -> int:
        return self._ref.get(int(block), 0)

    def is_shared(self, block: int) -> bool:
        return self.ref_count(block) > 1

    def blocks_needed(self, n_tokens: int) -> int:
        """Blocks covering ``n_tokens`` cache slots (at least one: every
        sequence owns a block from its first token) — the one shared
        block-count formula (``config.blocks_for_tokens``)."""
        return blocks_for_tokens(n_tokens, self.block_size)

    def can_allocate(self, n_blocks: int) -> bool:
        return self.num_free >= int(n_blocks)

    def can_allocate_shared(self, n_tokens: int,
                            shared: Sequence[int] = (),
                            cow_src: Optional[int] = None) -> bool:
        """Whether an admission with ``shared`` prefix blocks (mapped in
        by refcount, consuming nothing) and an optional COW source can
        take its remaining fresh blocks. Shared/source blocks currently
        parked on the evictable list stop being reclaimable the moment
        they are pinned, so they are discounted from the budget."""
        fresh = self.blocks_needed(n_tokens) - len(shared)
        pinned = [b for b in shared if b in self._evictable]
        if cow_src is not None and cow_src in self._evictable:
            pinned.append(cow_src)
        return self.num_free - len(pinned) >= fresh

    # ------------------------------------------------------------------
    def _take(self) -> int:
        """Claim one physical block: the free list first, else recycle
        the least-recently-used evictable block (notifying the prefix
        cache so its trie entry dies with the bytes)."""
        if self._free:
            return self._free.pop()
        if self._evictable:
            block, _ = self._evictable.popitem(last=False)
            self._cached.discard(block)
            self.evictions += 1
            if self.on_evict is not None:
                self.on_evict(block)
            return block
        raise RuntimeError("cache pool exhausted")

    def _pin(self, block: int):
        """Add one reference to a cached block (a new sequence maps it
        into its table, or a COW copy is pending from it)."""
        block = int(block)
        if block in self._evictable:
            del self._evictable[block]
        self._ref[block] = self._ref.get(block, 0) + 1

    def _unref(self, block: int):
        ref = self._ref.get(block, 0) - 1
        if ref > 0:
            self._ref[block] = ref
            return
        self._ref.pop(block, None)
        if block in self._cached:
            # the prefix cache still indexes it: park on the LRU tier
            # (most-recently-released = last out)
            self._evictable[block] = None
            self._evictable.move_to_end(block)
        else:
            self._free.append(block)

    # ------------------------------------------------------------------
    def allocate(self, seq_id: str, n_tokens: int,
                 shared: Sequence[int] = (),
                 cow_src: Optional[int] = None) -> np.ndarray:
        """Allocate blocks covering ``n_tokens`` and return the sequence's
        ``[max_blocks_per_seq]`` int32 block table (unused tail = garbage
        block).

        ``shared`` maps already-cached full prefix blocks read-only into
        the front of the table (refcount++, no storage consumed);
        ``cow_src`` names a cached partially-filled block whose contents
        the first fresh block must receive a device-side copy of before
        any append — the source is pinned until :meth:`cow_done` (or
        release) so a concurrent allocation cannot evict it mid-copy.

        Raises on double allocation or exhaustion — admission control
        must check :meth:`can_allocate_shared` first.
        """
        if seq_id in self._owned:
            raise ValueError(f"sequence {seq_id!r} already owns blocks")
        need = self.blocks_needed(n_tokens)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"{n_tokens} tokens need {need} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq}")
        if not self.can_allocate_shared(n_tokens, shared, cow_src):
            raise RuntimeError(
                f"cache pool exhausted: {need - len(shared)} fresh blocks "
                f"needed, {self.num_free} reclaimable")
        if len(shared) >= need:
            raise ValueError(
                f"shared prefix ({len(shared)} blocks) must leave at least "
                f"one fresh block of the {need} needed")
        # pin shared + COW source FIRST: fresh takes below may evict, and
        # they must never evict a block this admission is about to read
        for b in shared:
            self._pin(b)
        if cow_src is not None:
            self._pin(cow_src)
            self._cow_pending[seq_id] = int(cow_src)
        fresh = [self._take() for _ in range(need - len(shared))]
        for b in fresh:
            self._ref[b] = self._ref.get(b, 0) + 1
        blocks = [int(b) for b in shared] + fresh
        self._owned[seq_id] = blocks
        table = np.full((self.max_blocks_per_seq,), GARBAGE_BLOCK, np.int32)
        table[:need] = blocks
        return table

    def cow_done(self, seq_id: str):
        """The engine finished the device-side block copy: drop the
        pending pin on the COW source (it may become evictable again)."""
        src = self._cow_pending.pop(seq_id, None)
        if src is not None:
            self._unref(src)

    # ------------------------------------------------------------------
    # speculative window (draft-and-verify decoding)
    # ------------------------------------------------------------------
    def speculate(self, seq_id: str, n_tokens: int) -> List[int]:
        """Open (or extend) ``seq_id``'s speculative write window: its
        block set grows to cover ``n_tokens`` cache rows so a verify
        step can scatter up to ``k`` draft tokens past the committed
        length. Blocks the sequence already owns are reused in place
        (the worst-case admission reservation usually covers the whole
        window — then this is pure ledger work); only coverage past them
        takes fresh blocks, and those are the window's droppable tail.
        Returns the freshly granted blocks (often ``[]``).

        Re-speculating with a window still open is legal and keeps the
        ORIGINAL base — a verify dispatch that died between draft and
        commit (chaos, failover) must be able to retry from the same
        committed state without leaking its first grant.
        """
        blocks = self._owned.get(seq_id)
        if blocks is None:
            raise ValueError(f"sequence {seq_id!r} owns no blocks to "
                             "speculate past")
        need = self.blocks_needed(n_tokens)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"speculative window of {n_tokens} tokens needs {need} "
                f"blocks > max_blocks_per_seq {self.max_blocks_per_seq}")
        self._spec_base.setdefault(seq_id, len(blocks))
        extra = need - len(blocks)
        if extra <= 0:
            return []
        if self.num_free < extra:
            raise RuntimeError(
                f"cache pool exhausted: speculative window needs {extra} "
                f"fresh blocks, {self.num_free} reclaimable")
        fresh = [self._take() for _ in range(extra)]
        for b in fresh:
            self._ref[b] = self._ref.get(b, 0) + 1
        blocks.extend(fresh)
        return fresh

    def commit_speculative(self, seq_id: str, n_tokens: int) -> int:
        """Close the speculative window, keeping blocks that cover the
        accepted prefix's ``n_tokens`` cache rows: they simply fold into
        the sequence's owned set (their KV bytes are already in place —
        no copies), while granted blocks past the commit point return to
        the free list. Returns how many blocks were dropped. No open
        window is a no-op (a pure-ledger window never granted blocks).
        """
        base = self._spec_base.pop(seq_id, None)
        if base is None:
            return 0
        blocks = self._owned.get(seq_id)
        if not blocks:
            return 0
        keep = max(base, self.blocks_needed(n_tokens))
        dropped = blocks[keep:]
        del blocks[keep:]
        for b in reversed(dropped):
            self._unref(b)
        return len(dropped)

    def drop_speculative(self, seq_id: str) -> int:
        """Reject the whole window: every granted block returns to the
        free list, the owned set is exactly as before ``speculate()``."""
        return self.commit_speculative(seq_id, 0)

    def speculating(self, seq_id: str) -> bool:
        return seq_id in self._spec_base

    # ------------------------------------------------------------------
    def release(self, seq_id: str) -> int:
        """Drop a finished sequence's references; returns how many table
        entries were released. A block's storage is reclaimed only at
        refcount zero — shared prefix blocks survive their co-owners, and
        cached blocks park on the evictable LRU instead of the free list.
        Unknown ids are a no-op (a shed request never owned blocks)."""
        self.cow_done(seq_id)
        self._spec_base.pop(seq_id, None)
        blocks = self._owned.pop(seq_id, None)
        if not blocks:
            return 0
        for b in reversed(blocks):
            self._unref(b)
        return len(blocks)

    def owned(self, seq_id: str) -> List[int]:
        return list(self._owned.get(seq_id, ()))

    # ------------------------------------------------------------------
    # prefix-cache surface
    # ------------------------------------------------------------------
    def mark_cached(self, block: int):
        """Register a block as indexed by the prefix cache: from now on
        its storage survives its last owner (evictable LRU, not the free
        list) until :meth:`drop_cached` or LRU recycling."""
        block = int(block)
        if block == GARBAGE_BLOCK:
            raise ValueError("the garbage block can never be cached")
        self._cached.add(block)

    def drop_cached(self, block: int):
        """The prefix cache stopped indexing a block (subtree pruned):
        if it was parked evictable it returns to the free list now; a
        live owner keeps it alive as a plain private block."""
        block = int(block)
        self._cached.discard(block)
        if block in self._evictable:
            del self._evictable[block]
            self._free.append(block)

    def touch(self, blocks: Iterable[int]):
        """LRU hit: matched blocks move to the most-recently-used end of
        the evictable ladder (live blocks are untouched — they are not
        eviction candidates)."""
        for b in blocks:
            b = int(b)
            if b in self._evictable:
                self._evictable.move_to_end(b)
