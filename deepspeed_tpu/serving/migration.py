"""Live KV-block migration: move a sequence's committed state between
replicas instead of replaying its work.

The paged pool makes this cheap to say and do: a sequence's KV is a set
of pool blocks named by its block table, so migration is a block-granular
transfer plus a table rewrite — ``ServingEngine.export_sequence`` gathers
the covered blocks' rows (every cache leaf: int8 side pools and their
scales ride the same indices, per-TP-shard chunks along the head axis),
``import_sequence`` allocates blocks on the target, scatters the rows at
exactly the pool rows every later ``paged_write_rows``-indexed program
addresses through the rewritten table, and splices the request into a
free slot mid-stream — NO prefill dispatch, counters intact, greedy
continuation bit-identical to never having moved.

:class:`Migrator` is the host-side orchestrator (this module never
imports jax — the device work lives behind the engine seams) and the one
place the move is observed: a ``migrate`` span inside the request's
existing trace and the ``ds_migration_*`` metric family. Three consumers
sit above it:

- router failover — a tripped/stalled replica whose pool is still
  readable migrates its in-flight work instead of replaying it (and
  ``do_sample`` requests with a delivered prefix stop shedding, because
  their KV moves with them); a hard crash keeps the replay path;
- fleet drain — ``start_drain`` migrates in-flight work to survivors,
  demoting ``drain_timeout_steps`` from the plan to the fallback;
- rebalance — the fleet manager migrates work off the most fragmented
  replica when the ``kv_fragmentation`` gauge crosses the configured
  threshold.

Failure contract (chaos-proven): any fault between export and the
target's table commit leaves the source untouched and the target's
allocation released — the caller falls back to replay with exactly-once
delivery. The move is committed only when :meth:`Migrator.migrate`
returns a result.
"""

import time
from typing import Any, Dict, Optional

from deepspeed_tpu.runtime.resilience.chaos import raise_if
from deepspeed_tpu.serving.config import MigrationConfig
from deepspeed_tpu.telemetry.registry import NULL_REGISTRY
from deepspeed_tpu.telemetry.tracing import NULL_TRACER, to_ns

__all__ = ["Migrator", "resolve_migration"]


def resolve_migration(config) -> Optional[MigrationConfig]:
    """Normalize a ``serving.migration`` value (None / dict /
    :class:`MigrationConfig`) — None means migration does not exist and
    every consumer keeps its pre-migration behavior."""
    if config is None:
        return None
    if isinstance(config, MigrationConfig):
        return config
    return MigrationConfig(**dict(config))


class Migrator:
    """One migration primitive: ``export → transfer → import → detach``,
    observed as one ``migrate`` span and one ``ds_migration_attempts``
    sample per call. Host-only — both replicas' device work happens
    behind their own engine seams, so this object is safe to hold in the
    jax-free router/fleet layer."""

    #: attempt outcomes (the ``outcome`` label of
    #: ``ds_migration_attempts_total``); everything except ``ok`` also
    #: bumps ``ds_migration_fallbacks_total`` — the caller replays.
    OUTCOMES = ("ok", "no_surface", "export_none", "import_none", "error")

    def __init__(self, config=None, tracer=None, metrics=None,
                 clock=time.monotonic):
        self.config = resolve_migration(config)
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._metrics = metrics if metrics is not None else NULL_REGISTRY
        self.clock = clock

    # ---- consumer gates (config absent/disabled => everything off) ----
    @property
    def enabled(self) -> bool:
        return self.config is not None and self.config.enabled

    def allows(self, consumer: str) -> bool:
        """Whether ``consumer`` (``failover`` | ``drain`` |
        ``rebalance``) may migrate."""
        return self.enabled and bool(getattr(self.config, consumer, False))

    # ------------------------------------------------------------------
    def migrate(self, source, target, request_id: str, *,
                import_id: Optional[str] = None,
                deadline_ms: Optional[float] = None,
                stream=None, trace=None, parent=None,
                import_trace: Optional[Dict] = None,
                src: Any = None, dst: Any = None,
                reason: str = "failover") -> Optional[Dict]:
        """Move one in-flight sequence from ``source`` to ``target``.

        Returns ``{"request", "blocks", "wire_bytes", "stall_ms",
        "outcome"}`` on success (``request`` is the target-side
        :class:`~deepspeed_tpu.serving.request.Request`, already live in
        a decode slot), or None when the move could not happen — export
        declined (source has no migratable state or no export surface),
        import declined (target cannot land it), or a fault fired
        mid-transfer. None ALWAYS means the target holds nothing and the
        source was not detached: the caller's replay path stays correct.

        ``trace``/``parent`` attach the ``migrate`` span to the
        request's existing client trace; ``src``/``dst`` label the span
        with replica identities; ``import_id`` renames the request on
        the target (the router's per-attempt proxy ids)."""
        t0 = self.clock()
        outcome, export, req = "ok", None, None
        try:
            exporter = getattr(source, "export_sequence", None)
            if exporter is None:
                outcome = "no_surface"
            else:
                export = exporter(request_id)
                if export is None:
                    outcome = "export_none"
            if export is not None:
                # the wire: host-to-host block rows in flight (the chaos
                # flaky-transfer seam fires here, between export and the
                # target's import)
                raise_if("serving.migration.transfer", detail=request_id)
                req = target.import_sequence(
                    export, deadline_ms=deadline_ms, stream=stream,
                    request_id=import_id, trace=import_trace)
                if req is None:
                    outcome = "import_none"
        except Exception:
            # export crash, transfer fault, or import fault past the
            # commit seam: the target released its allocation on the way
            # out and the source's committed state is untouched
            outcome, req = "error", None
        if req is not None:
            # commit point: the target owns the sequence — detach the
            # source copy (host-only bookkeeping, cannot fail partway)
            detach = getattr(source, "migrate_out", None)
            if detach is not None:
                detach(request_id)
        t1 = self.clock()
        stall_ms = round(1e3 * max(t1 - t0, 0.0), 3)
        blocks = int(export["blocks"]) if export else 0
        wire = int(export["wire_bytes"]) if export else 0
        m = self._metrics
        m.counter("ds_migration_attempts_total", ("outcome",)).labels(
            outcome=outcome).inc()
        if req is None:
            m.counter("ds_migration_fallbacks_total").inc()
        else:
            m.counter("ds_migration_blocks_moved_total").inc(blocks)
            m.counter("ds_migration_wire_bytes_total").inc(wire)
        m.histogram("ds_migration_stall_ms").observe(stall_ms)
        if trace is not None:
            self._tracer.record_span(
                "migrate", trace, to_ns(t0), to_ns(t1), parent=parent,
                request_id=request_id, src=src, dst=dst, reason=reason,
                outcome=outcome, blocks=blocks, wire_bytes=wire)
        if req is None:
            return None
        return {"request": req, "blocks": blocks, "wire_bytes": wire,
                "stall_ms": stall_ms, "outcome": outcome}
