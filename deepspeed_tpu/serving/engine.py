"""Serving runtime: continuous batching over a paged KV cache.

Wraps an :class:`~deepspeed_tpu.inference.engine.InferenceEngine` (its
params, sharding, dtype/quantization and telemetry/resilience managers)
with a request-level scheduler and a small FIXED set of compiled
programs:

- ``serving.prefill[T=b]`` — one per prompt bucket ``b`` (a small fixed
  set, powers of two by default): right-pads the prompt to the bucket,
  scatters its KV into the sequence's pool blocks (pad tail into the
  garbage block) and returns the first sampled token;
- ``serving.decode[slots=N]`` — ONE program for the fixed slot batch:
  every active sequence advances one token against its own block table
  and length; idle slots compute into the garbage block and are ignored;
- ``serving.chunk[T=c]`` — the serving fast path's third program
  (compiled only when ``prefill_chunk_tokens`` or ``prefix_cache`` is
  on): writes ``c`` prompt tokens at the sequence's current length and
  attends them against the pool — the program behind both *chunked
  prefill* (long prompts advance one budgeted chunk per step instead of
  monopolizing a whole-prompt program, collapsing the bucket ladder to
  one shape) and *prefix-cache tail prefill* (a request whose prompt
  prefix is already pooled writes only the unmatched tail);
- ``serving.cow`` — copy one pool block's rows to another (every cache
  leaf, scales included): the device half of partial-tail copy-on-write;
- ``serving.verify[slots=N,k=K]`` — speculative decoding's whole device
  surface (compiled only when ``serving.speculative`` is on, REPLACING
  the decode program in the step loop): every slot advances ``K + 1``
  query rows — the pending last token plus up to ``K`` host-proposed
  draft tokens, right-padded against the garbage block — through the
  multi-query-row paged attention kernel in ONE dispatch, returning the
  target model's greedy token at every row. The host keeps the longest
  proposal prefix the greedy oracle agrees with (1 to K+1 tokens per
  step for one dispatch), commits the accepted extent through the block
  manager's speculative ledger, and drops the rejected tail without
  copies — rejected rows sit past the committed length, masked out of
  every later attention window and overwritten by the next step's
  writes. Greedy output is bit-identical to non-speculative decode.

Finished sequences are evicted and queued requests spliced into free
slots *between* decode steps — shapes never change, so the steady-state
retrace count is zero (pinned by the telemetry compile watchdog in
``tests/unit/test_serving.py``). Greedy tokens bit-match per-request
``generate()`` output: the paged decode gathers pool blocks back into
logical order, so the math matches the dense append-cache program
term for term. With ``prefix_cache`` on, a request admitting behind an
identical system prompt maps those blocks read-only (a
:class:`~deepspeed_tpu.serving.blocks.BlockManager` refcount bump) and
prefills only its tail; with ``kv_cache_dtype: "int8"`` the pools store
per-row-quantized KV at a quarter of the bytes. All three knobs default
off, and off means byte-identical compiled programs.

Per-request telemetry (kind ``serving``: TTFT, queue wait, tokens/s,
shed) rides the unified event stream; the resilience hang watchdog sees
begin/heartbeat/abandon brackets so a wedged decode collective is a
detected stall while an idle server is never judged hung.
"""

import collections
import contextlib
import time
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.resilience.chaos import raise_if
from deepspeed_tpu.serving.blocks import BlockManager
from deepspeed_tpu.serving.config import (ServingConfig, blocks_for_tokens,
                                          bucket_for, resolve_buckets)
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.request import FINISHED, Request
from deepspeed_tpu.serving.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.serving.spec_decode import build_proposer
from deepspeed_tpu.telemetry.tracing import end_span, to_ns
from deepspeed_tpu.utils.logging import log_dist


def _model_window(model_config) -> Optional[int]:
    return (getattr(model_config, "n_positions", None)
            or getattr(model_config, "max_position_embeddings", None))


class ServingEngine:
    def __init__(self, model_or_engine, config=None, draft_model=None,
                 clock=time.monotonic, **kwargs):
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.runtime.config import DeepSpeedConfigError

        self._jax, self._jnp = jax, jnp
        # injectable timebase: every request timestamp, deadline sweep
        # and span bracket reads THIS clock, so the trace-replay harness
        # can drive a real engine faster than real time (the router and
        # fleet manager share the same seam)
        self.clock = clock
        if isinstance(model_or_engine, InferenceEngine):
            if config is not None or kwargs:
                raise ValueError(
                    "pass config/kwargs to the InferenceEngine, not again "
                    "to ServingEngine when wrapping one")
            self.engine = model_or_engine
            self._owns_engine = False
        else:
            self.engine = InferenceEngine(model_or_engine, config=config,
                                          **kwargs)
            self._owns_engine = True
        scfg = self.engine._serving_cfg
        if scfg is None or not scfg.enabled:
            raise DeepSpeedConfigError(
                "ServingEngine needs a `serving` block in the inference "
                'config, e.g. init_inference(model, serving={"block_size": '
                '16, "decode_slots": 4})')
        self.config: ServingConfig = scfg

        mcfg = self.engine.model_config
        if mcfg is None or not hasattr(mcfg, "for_paged_decode"):
            raise ValueError(
                "serving needs a model whose config provides "
                "for_paged_decode() — the canonical decoder family "
                "(GPT2LMHeadModel and its OPT/BLOOM/GPT-J/NeoX variants)")
        window = _model_window(mcfg)
        self.max_len = int(self.config.max_model_len or window or 1024)
        if window:
            self.max_len = min(self.max_len, int(window))
        bs = self.config.block_size
        self.blocks_per_seq = blocks_for_tokens(self.max_len, bs)
        # garbage block + conservative worst-case reservation per slot:
        # admission never admits work the pool cannot finish
        self.num_blocks = int(self.config.num_blocks) or (
            1 + self.config.decode_slots * self.blocks_per_seq)
        self.buckets = resolve_buckets(self.config.prompt_buckets,
                                       self.max_len, floor=bs)
        if self.config.kv_cache_dtype:
            dcfg = mcfg.for_paged_decode(self.num_blocks, bs,
                                         kv_dtype=self.config.kv_cache_dtype)
        else:
            # keyword omitted on purpose: a model family predating the
            # kv_dtype knob keeps serving exactly as before
            dcfg = mcfg.for_paged_decode(self.num_blocks, bs)
        self._dmodule = type(self.engine.module)(dcfg)
        self.block_mgr = BlockManager(self.num_blocks, bs,
                                      self.blocks_per_seq)
        self.prefix = (PrefixCache(self.block_mgr)
                       if self.config.prefix_cache else None)
        self.telemetry = self.engine.telemetry
        self.resilience = self.engine.resilience
        # span tracer (inert unless telemetry.tracing is on): request
        # traces — queue/prefill/cow/decode legs — ride the event stream
        self._tracer = self.telemetry.tracer
        self.sched = ContinuousBatchingScheduler(
            self.config, self.block_mgr, self.max_len, self.buckets,
            clock=self.clock, prefix_cache=self.prefix,
            tracer=self._tracer)

        self.cache = self._init_cache()
        self._tables = np.full(
            (self.config.decode_slots, self.blocks_per_seq), 0, np.int32)
        self._last_tokens = np.zeros((self.config.decode_slots,), np.int32)
        self._lengths = np.zeros((self.config.decode_slots,), np.int32)
        self._prefill_fns: Dict[int, object] = {}
        self._decode_fn = None
        # chunked / prefix-continued prefill state: a slot mid-prefill is
        # NOT in the decode batch (its row of self._tables stays pointed
        # at the garbage block) until its whole prompt is written
        self.chunk_tokens = int(self.config.prefill_chunk_tokens)
        self._prefilling: Dict[int, Request] = {}
        self._pf_tables: Dict[int, np.ndarray] = {}
        self._pf_pos: Dict[int, int] = {}
        self._pf_next = 0  # round-robin cursor over prefilling slots
        self._chunk_fns: Dict[int, object] = {}
        self._cow_fn = None
        # live KV migration import programs, one per covered-block count
        # (built lazily — a fleet that never migrates compiles nothing)
        self._migrate_fns: Dict[int, object] = {}
        # speculative decoding: host-side proposer + the ONE compiled
        # k-token verify program (replaces the decode program in the
        # step loop; None => the decode path is exactly as before)
        self._proposer = build_proposer(self.config.speculative,
                                        draft_model=draft_model)
        self.spec_k = (int(self.config.speculative.num_speculative_tokens)
                       if self._proposer is not None else 0)
        self._verify_fn = None
        self._rng = jax.random.PRNGKey(self.config.seed)
        # reproducible keyed sampling (serving.sampling): per-slot
        # sampling state rides the compiled programs as traced arrays —
        # the key for request R's token at position P folds (R's seed, P)
        # inside the program, so the emitted token is independent of slot
        # index, batch composition and tp layout. With the block absent
        # these arrays do not exist and every program is byte-identical.
        self._keyed = bool(self.config.sampling
                           and self.config.sampling.enabled)
        if self._keyed:
            n = self.config.decode_slots
            self._seeds = np.zeros((n,), np.uint32)
            self._samp_on = np.zeros((n,), np.int32)
            self._temps = np.ones((n,), np.float32)
            self._top_ks = np.zeros((n,), np.int32)
            self._top_ps = np.zeros((n,), np.float32)
        self._step_count = 0
        # speculation counters over the stats window (reset_stats zeroes
        # them WITH the records deque — the bounded records alone would
        # decay any per-step ratio on a long-running server)
        self._spec_steps = 0
        self._window_draft_tokens = 0
        self._window_accepted_tokens = 0
        # prefix-cache window counters (the hit-rate GAUGE's input —
        # recomputing from the bounded records deque per step would both
        # cost a scan and decay on long runs)
        self._window_prompt_tokens = 0
        self._window_hit_tokens = 0
        self._finished_count = 0
        # live metrics plane: the telemetry manager's registry (the
        # inert NULL_REGISTRY unless telemetry.metrics_port/metrics_file
        # armed it), so every instrumentation site runs unconditional
        self._metrics = self.telemetry.metrics
        # bounded retention (a long-running server must not accumulate a
        # dead Request per served request until OOM — same contract as
        # the telemetry manager's bounded event tail); stats() percentiles
        # therefore cover the most recent window
        self.finished = collections.deque(maxlen=1024)
        self.records = collections.deque(maxlen=4096)
        log_dist(
            f"ServingEngine: slots={self.config.decode_slots} "
            f"block_size={bs} num_blocks={self.num_blocks} "
            f"buckets={self.buckets} max_len={self.max_len}", ranks=[0])

    # ------------------------------------------------------------------
    def _init_cache(self):
        """Zeroed per-layer KV pools, shaped by tracing the paged decode
        module's init without running it (eval_shape: no compute, no
        params materialized). Placed with the mesh shardings the
        compiled programs emit — ``decode_cache_specs``: on a tp>1 mesh
        the key/value pools (and their int8 scale side pools) live
        HEAD-SHARDED over the tp axis, a per-shard KV pool per device
        group, exactly the layout the TP-aware paged Pallas kernel
        consumes — so the FIRST prefill's argument signature already
        matches steady state (a `jnp.zeros` pool would carry
        SingleDeviceSharding and cost that bucket one spurious
        retrace)."""
        jax, jnp = self._jax, self._jnp
        from deepspeed_tpu.module_inject.policies import decode_cache_specs

        pg = {"block_tables": jnp.zeros((1, self.blocks_per_seq), jnp.int32),
              "lengths": jnp.zeros((1,), jnp.int32),
              "num_valid": jnp.zeros((1,), jnp.int32), "prefill": True}
        shapes = jax.eval_shape(
            lambda: self._dmodule.init(jax.random.PRNGKey(0),
                                       jnp.zeros((1, 1), jnp.int32),
                                       paging=pg))
        shardings = decode_cache_specs(shapes["cache"], self.engine.mesh)
        return jax.tree_util.tree_map(
            lambda s, sh: jax.device_put(jnp.zeros(s.shape, s.dtype), sh),
            shapes["cache"], shardings)

    def _donate(self):
        # the old pool is dead after every call — donate it so steady-state
        # serving holds ONE pool allocation (CPU jax warns instead of
        # donating; skip there)
        return (1,) if self._jax.default_backend() != "cpu" else ()

    def _sample(self, logits, rng):
        from deepspeed_tpu.inference.engine import sample_logits

        sc = self.config
        return sample_logits(logits, rng, sc.temperature, sc.do_sample,
                             sc.top_k, sc.top_p)

    def _build_prefill(self, T: int):
        jax, jnp = self._jax, self._jnp
        dmodule, dequant = self._dmodule, self.engine._dequantize
        logits_of = self.engine._logits_of
        if self._keyed:
            from deepspeed_tpu.ops.sampling import keyed_sample

            def kfn(qparams, cache, ids, tables, num_valid, seeds, flags,
                    temps, top_ks, top_ps):
                params = dequant(qparams)
                paging = {"block_tables": tables,
                          "lengths": jnp.zeros((ids.shape[0],), jnp.int32),
                          "num_valid": num_valid, "prefill": True}
                out, vars_ = dmodule.apply(
                    {"params": params, "cache": cache}, ids,
                    mutable=["cache"], paging=paging)
                logits = logits_of(out)
                last = jnp.take_along_axis(
                    logits, (num_valid - 1)[:, None, None], axis=1)[:, 0]
                # the first generated token's absolute position is the
                # prompt length — num_valid itself
                tok = keyed_sample(last, seeds, num_valid, flags, temps,
                                   top_ks, top_ps)
                return tok, vars_["cache"]

            return self.engine.telemetry.watch_jit(
                jax.jit(kfn, donate_argnums=self._donate()),
                f"serving.prefill[T={T}]")

        def fn(qparams, cache, ids, tables, num_valid, rng):
            params = dequant(qparams)
            paging = {"block_tables": tables,
                      "lengths": jnp.zeros((ids.shape[0],), jnp.int32),
                      "num_valid": num_valid, "prefill": True}
            out, vars_ = dmodule.apply({"params": params, "cache": cache},
                                       ids, mutable=["cache"], paging=paging)
            logits = logits_of(out)
            # the request's next token depends on its LAST REAL position
            # (right padding: index num_valid-1)
            last = jnp.take_along_axis(
                logits, (num_valid - 1)[:, None, None], axis=1)[:, 0]
            return self._sample(last, rng), vars_["cache"]

        return self.engine.telemetry.watch_jit(
            jax.jit(fn, donate_argnums=self._donate()),
            f"serving.prefill[T={T}]")

    def _build_decode(self):
        jax, jnp = self._jax, self._jnp
        dmodule, dequant = self._dmodule, self.engine._dequantize
        logits_of = self.engine._logits_of
        if self._keyed:
            from deepspeed_tpu.ops.sampling import keyed_sample

            def kfn(qparams, cache, tokens, tables, lengths, seeds, flags,
                    temps, top_ks, top_ps):
                params = dequant(qparams)
                paging = {"block_tables": tables, "lengths": lengths,
                          "num_valid": jnp.ones_like(lengths),
                          "prefill": False}
                out, vars_ = dmodule.apply(
                    {"params": params, "cache": cache}, tokens,
                    mutable=["cache"], paging=paging)
                logits = logits_of(out)[:, -1]
                # this step emits the token at absolute position
                # lengths + 1 (lengths tokens are pooled; the pending
                # last token sits at position lengths)
                tok = keyed_sample(logits, seeds, lengths + 1, flags,
                                   temps, top_ks, top_ps)
                return tok, vars_["cache"]

            return self.engine.telemetry.watch_jit(
                jax.jit(kfn, donate_argnums=self._donate()),
                f"serving.decode[slots={self.config.decode_slots}]")

        def fn(qparams, cache, tokens, tables, lengths, rng):
            params = dequant(qparams)
            paging = {"block_tables": tables, "lengths": lengths,
                      "num_valid": jnp.ones_like(lengths),
                      "prefill": False}
            out, vars_ = dmodule.apply({"params": params, "cache": cache},
                                       tokens, mutable=["cache"],
                                       paging=paging)
            logits = logits_of(out)[:, -1]
            return self._sample(logits, rng), vars_["cache"]

        return self.engine.telemetry.watch_jit(
            jax.jit(fn, donate_argnums=self._donate()),
            f"serving.decode[slots={self.config.decode_slots}]")

    def _build_chunk(self, T: int):
        """One prefill chunk: write ``num_valid`` prompt tokens at the
        sequence's current pool length and attend them against everything
        already pooled (shared prefix blocks included) plus themselves,
        causally. The sampled token at the last REAL position is
        meaningful only on the final chunk — it is the request's first
        generated token."""
        jax, jnp = self._jax, self._jnp
        dmodule, dequant = self._dmodule, self.engine._dequantize
        logits_of = self.engine._logits_of
        if self._keyed:
            from deepspeed_tpu.ops.sampling import keyed_sample

            def kfn(qparams, cache, ids, tables, lengths, num_valid,
                    seeds, flags, temps, top_ks, top_ps):
                params = dequant(qparams)
                paging = {"block_tables": tables, "lengths": lengths,
                          "num_valid": num_valid, "prefill": False}
                out, vars_ = dmodule.apply(
                    {"params": params, "cache": cache}, ids,
                    mutable=["cache"], paging=paging)
                logits = logits_of(out)
                last = jnp.take_along_axis(
                    logits, (num_valid - 1)[:, None, None], axis=1)[:, 0]
                # only the FINAL chunk's token is consumed, at absolute
                # position lengths + num_valid = the full prompt length
                # — identical to the whole-prompt prefill's fold-in, so
                # chunked and unchunked admission sample the same token
                tok = keyed_sample(last, seeds, lengths + num_valid,
                                   flags, temps, top_ks, top_ps)
                return tok, vars_["cache"]

            return self.engine.telemetry.watch_jit(
                jax.jit(kfn, donate_argnums=self._donate()),
                f"serving.chunk[T={T}]")

        def fn(qparams, cache, ids, tables, lengths, num_valid, rng):
            params = dequant(qparams)
            paging = {"block_tables": tables, "lengths": lengths,
                      "num_valid": num_valid, "prefill": False}
            out, vars_ = dmodule.apply({"params": params, "cache": cache},
                                       ids, mutable=["cache"], paging=paging)
            logits = logits_of(out)
            last = jnp.take_along_axis(
                logits, (num_valid - 1)[:, None, None], axis=1)[:, 0]
            return self._sample(last, rng), vars_["cache"]

        return self.engine.telemetry.watch_jit(
            jax.jit(fn, donate_argnums=self._donate()),
            f"serving.chunk[T={T}]")

    def _build_verify(self):
        """The k-token verify program — speculative decoding's single
        compiled surface. Every slot advances ``T = k + 1`` query rows
        at once (pending last token + the proposals, right-padded), the
        multi-query-row paged attention kernel masks each row causally
        at ``lengths[b] + row``, ``num_valid`` routes pad rows' KV
        writes into the garbage block, and the program returns the
        greedy token at EVERY row — the host's exact accept oracle. Row
        0's math is the decode program's term for term, so a verify
        step that accepts nothing still emits the identical token the
        plain decode step would have."""
        jax, jnp = self._jax, self._jnp
        dmodule, dequant = self._dmodule, self.engine._dequantize
        logits_of = self.engine._logits_of

        def fn(qparams, cache, tokens, tables, lengths, num_valid, rng):
            params = dequant(qparams)
            paging = {"block_tables": tables, "lengths": lengths,
                      "num_valid": num_valid, "prefill": False}
            out, vars_ = dmodule.apply({"params": params, "cache": cache},
                                       tokens, mutable=["cache"],
                                       paging=paging)
            logits = logits_of(out)                       # [N, k+1, V]
            n, t, v = logits.shape
            toks = self._sample(logits.reshape(n * t, v), rng)
            return toks.reshape(n, t), vars_["cache"]

        return self.engine.telemetry.watch_jit(
            jax.jit(fn, donate_argnums=self._donate()),
            f"serving.verify[slots={self.config.decode_slots},"
            f"k={self.spec_k}]")

    def _build_cow(self):
        """Copy one pool block's rows onto another across every cache
        leaf (key/value pools and, under int8 KV, their scale side
        pools) — the device half of partial-tail copy-on-write. Pool
        leaves all end in ``[num_blocks, block_size, H, *]`` (with an
        optional leading scanned-layer axis), so the block axis is
        always ``ndim - 4``."""
        jax = self._jax

        def fn(cache, src, dst):
            def copy(p):
                ax = p.ndim - 4
                row = jax.lax.dynamic_index_in_dim(p, src, axis=ax,
                                                   keepdims=False)
                return jax.lax.dynamic_update_index_in_dim(p, row, dst, ax)

            return jax.tree_util.tree_map(copy, cache)

        donate = (0,) if self._jax.default_backend() != "cpu" else ()
        return self.engine.telemetry.watch_jit(
            jax.jit(fn, donate_argnums=donate), "serving.cow")

    def _build_migrate(self, B: int):
        """Scatter ``B`` migrated pool blocks (every cache leaf — K/V
        pools and, under int8 KV, their scale side pools ride the same
        block indices) onto this replica's pool at the freshly allocated
        destination blocks. The import half of live KV migration: rows
        land on exactly the pool rows every later ``paged_write_rows``/
        paged-gather computation addresses through the rewritten block
        table, so the resumed decode is bit-identical to never having
        moved. Same axis convention as the cow program: pool leaves all
        end in ``[num_blocks, block_size, H, *]`` (optional leading
        scanned-layer axis), so the block axis is always ``ndim - 4``."""
        jax, jnp = self._jax, self._jnp

        def fn(cache, rows, dst):
            def scatter(p, r):
                ax = p.ndim - 4
                pm = jnp.moveaxis(p, ax, 0)
                rm = jnp.moveaxis(r, ax, 0)
                return jnp.moveaxis(pm.at[dst].set(rm), 0, ax)

            return jax.tree_util.tree_map(scatter, cache, rows)

        donate = (0,) if self._jax.default_backend() != "cpu" else ()
        return self.engine.telemetry.watch_jit(
            jax.jit(fn, donate_argnums=donate),
            f"serving.migrate[blocks={B}]")

    def _next_rng(self):
        self._rng, sub = self._jax.random.split(self._rng)
        return sub

    def _req_samp_args(self, req: Request):
        """The keyed prefill/chunk programs' per-request sampling row
        ([1]-shaped, matching their batch of one). Greedy requests ride
        with flag 0 — the argmax leg, bit-identical to the rng path."""
        jnp = self._jnp
        on = 1 if req.do_sample else 0
        return (jnp.asarray([req.seed or 0], jnp.uint32),
                jnp.asarray([on], jnp.int32),
                jnp.asarray([req.temperature
                             if req.temperature is not None else 1.0],
                            jnp.float32),
                jnp.asarray([req.top_k or 0], jnp.int32),
                jnp.asarray([req.top_p or 0.0], jnp.float32))

    def _slot_samp_args(self):
        """The keyed decode program's per-slot sampling arrays (idle and
        greedy slots carry flag 0)."""
        jnp = self._jnp
        return (jnp.asarray(self._seeds), jnp.asarray(self._samp_on),
                jnp.asarray(self._temps), jnp.asarray(self._top_ks),
                jnp.asarray(self._top_ps))

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 0, **kwargs) -> Request:
        """Admit one request (non-blocking). Returns the Request; its
        ``state`` is ``queued`` on success or ``shed`` (with
        ``finish_reason``) when admission control rejected it."""
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      **kwargs)
        if self.sched.submit(req):
            self.resilience.serving_request_begin()
            self.telemetry.emit("serving", "request.queued",
                                step=self._step_count,
                                request_id=req.request_id,
                                prompt_len=req.prompt_len)
        else:
            self._record(req, shed=True, began=False)
        return req

    # ------------------------------------------------------------------
    def step(self) -> List[Request]:
        """One scheduler iteration: abandon blown deadlines, splice queued
        requests into free slots, advance mid-prefill prompts one budgeted
        chunk, then advance every decode-ready sequence one token. Returns
        requests finished this step."""
        now = self.clock()
        done: List[Request] = []
        # deadline sweep over running work
        for slot, req in self.sched.running():
            if self.sched.expired(req, now):
                self._finish(req, "deadline", now, done)
        # splice admissions into free slots (no recompilation: bucket set)
        admitted, shed = self.sched.admit(now)
        for req in shed:
            self._record(req, shed=True, began=True)
        for slot, req, table in admitted:
            self._begin(slot, req, table, done)
        self._prefill_chunks(done)
        # one decode step for the whole slot batch (mid-prefill slots are
        # idle decode rows: garbage table, outputs ignored); with
        # speculation on, the verify program IS the decode step
        if any(slot not in self._prefilling
               for slot, _ in self.sched.running()):
            if self._proposer is not None:
                self._spec_step(done)
            else:
                self._decode_step(done)
        return done

    def _begin(self, slot: int, req: Request, table: np.ndarray,
               done: List[Request]):
        """Route a fresh admission: legacy whole-prompt bucketed prefill
        (the zero-feature path, program-identical to PR 4), or the
        chunked/prefix-continued path when the request has pooled prefix
        tokens to skip or chunking is on."""
        if req.cow is not None:
            # partial-tail copy-on-write: the matched block will be
            # appended to, so the request's own fresh block receives a
            # device copy of its rows before anything else runs; the
            # source unpins once the copy is in flight
            with self._req_span(req, "cow", src=req.cow[0],
                                dst=req.cow[1]):
                self._cow_copy(*req.cow)
            self.block_mgr.cow_done(req.request_id)
        if not self.chunk_tokens and req.cached_len == 0:
            self._prefill(slot, req, table, done)
            return
        self._prefilling[slot] = req
        self._pf_tables[slot] = table
        self._pf_pos[slot] = req.cached_len
        req.length = req.cached_len

    def _req_span(self, req: Request, name: str, **attrs):
        """Span bracket in ``req``'s trace (nullcontext when tracing is
        off or the request carries no context). Durations are host-side
        dispatch+sync walltime — the same clock every request timestamp
        already uses."""
        if not self._tracer.enabled or req.trace is None:
            return contextlib.nullcontext()
        return self._tracer.span(name, req.trace["trace"],
                                 parent=req.trace.get("serve_id"), **attrs)

    def _prefill(self, slot: int, req: Request, table: np.ndarray,
                 done: List[Request]):
        jnp = self._jnp
        T = bucket_for(req.prompt_len, self.buckets)
        if T not in self._prefill_fns:
            self._prefill_fns[T] = self._build_prefill(T)
        ids = np.zeros((1, T), np.int32)
        ids[0, :req.prompt_len] = req.prompt
        tail = (self._req_samp_args(req) if self._keyed
                else (self._next_rng(),))
        with self._req_span(req, "prefill", bucket=T,
                            prompt_len=req.prompt_len):
            tok, self.cache = self._prefill_fns[T](
                self.engine.params, self.cache, jnp.asarray(ids),
                jnp.asarray(table[None]),
                jnp.asarray([req.prompt_len], jnp.int32), *tail)
            tok = int(np.asarray(tok)[0])
        req.prefill_chunks = 1
        self._slot_live(slot, req, table, tok, done)

    # ------------------------------------------------------------------
    def _prefill_chunks(self, done: List[Request]):
        """Advance mid-prefill prompts. With chunking on, at most
        ``prefill_chunk_tokens`` prompt tokens are processed per step
        (round-robin over slots, so a long prompt never starves a later
        short one — the TTFT bound); with chunking off (prefix-cache
        tails) each pending tail completes now in one bucketed chunk."""
        if not self._prefilling:
            return
        budget = self.chunk_tokens or None
        spent = 0
        slots = sorted(self._prefilling)
        start = next((i for i, s in enumerate(slots)
                      if s >= self._pf_next), 0)
        for slot in slots[start:] + slots[:start]:
            req = self._prefilling.get(slot)
            if req is None:
                continue
            table = self._pf_tables[slot]
            pos = self._pf_pos[slot]
            remaining = req.prompt_len - pos
            step_len = (min(self.chunk_tokens, remaining)
                        if self.chunk_tokens else remaining)
            T = self.chunk_tokens or bucket_for(remaining, self.buckets)
            tok = self._chunk_call(req, table, pos, step_len, T)
            self._pf_pos[slot] = pos + step_len
            req.length = pos + step_len
            req.prefill_chunks += 1
            if pos + step_len >= req.prompt_len:
                del self._prefilling[slot]
                self._pf_tables.pop(slot, None)
                self._pf_pos.pop(slot, None)
                self._slot_live(slot, req, table, tok, done)
            if budget is not None:
                spent += step_len
                if spent >= budget:
                    self._pf_next = slot + 1
                    return

    def _chunk_call(self, req: Request, table: np.ndarray, pos: int,
                    step_len: int, T: int) -> int:
        jnp = self._jnp
        if T not in self._chunk_fns:
            self._chunk_fns[T] = self._build_chunk(T)
        ids = np.zeros((1, T), np.int32)
        ids[0, :step_len] = req.prompt[pos:pos + step_len]
        tail = (self._req_samp_args(req) if self._keyed
                else (self._next_rng(),))
        with self._req_span(req, "prefill_chunk", pos=pos,
                            tokens=step_len, bucket=T):
            tok, self.cache = self._chunk_fns[T](
                self.engine.params, self.cache, jnp.asarray(ids),
                jnp.asarray(table[None]), jnp.asarray([pos], jnp.int32),
                jnp.asarray([step_len], jnp.int32), *tail)
            return int(np.asarray(tok)[0])

    def _slot_live(self, slot: int, req: Request, table: np.ndarray,
                   tok: int, done: List[Request]):
        """Prompt fully pooled: index the prompt for future prefix hits,
        join the decode batch, and emit the first sampled token."""
        req.first_token_ts = self.clock()
        req.length = req.prompt_len
        self._tables[slot] = table
        self._lengths[slot] = req.prompt_len
        self._last_tokens[slot] = tok
        if self._keyed:
            self._set_samp_slot(slot, req)
        if self.prefix is not None:
            # BEFORE any finish: insertion must precede release so a
            # one-token request's blocks park evictable, not freed
            self.prefix.insert(req.prompt, table)
        finished = (tok == req.eos_token_id
                    or len(req.tokens) + 1 >= req.max_new_tokens)
        req.emit_token(tok, finished)
        if finished:
            reason = "eos" if tok == req.eos_token_id else "max_tokens"
            self._finish(req, reason, self.clock(), done)

    def _set_samp_slot(self, slot: int, req: Request):
        """Load one slot's sampling row from the request's (resolved)
        knobs — the ONLY per-slot sampler state; the key itself folds
        from (seed, position) inside the program every step."""
        self._seeds[slot] = int(req.seed or 0) & 0xFFFFFFFF
        self._samp_on[slot] = 1 if req.do_sample else 0
        self._temps[slot] = (req.temperature
                             if req.temperature is not None else 1.0)
        self._top_ks[slot] = req.top_k or 0
        self._top_ps[slot] = req.top_p or 0.0

    def _clear_samp_slot(self, slot: int):
        self._seeds[slot] = 0
        self._samp_on[slot] = 0
        self._temps[slot] = 1.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 0.0

    def _cow_copy(self, src: int, dst: int):
        jnp = self._jnp
        if self._cow_fn is None:
            self._cow_fn = self._build_cow()
        self.cache = self._cow_fn(self.cache, jnp.asarray(src, jnp.int32),
                                  jnp.asarray(dst, jnp.int32))

    def _decode_step(self, done: List[Request]):
        jnp = self._jnp
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        active = [(s, r) for s, r in self.sched.running()
                  if s not in self._prefilling]
        tokens = jnp.asarray(self._last_tokens[:, None])
        tail = (self._slot_samp_args() if self._keyed
                else (self._next_rng(),))
        toks, self.cache = self._decode_fn(
            self.engine.params, self.cache, tokens,
            jnp.asarray(self._tables), jnp.asarray(self._lengths),
            *tail)
        # the ONE designed host sync per decode step: sampled tokens must
        # reach the host to stream to callers and drive finish logic
        toks = np.asarray(toks)  # graft-lint: disable=GL04
        now = self.clock()
        self._step_count += 1
        self.telemetry.on_step_boundary(self._step_count,
                                        samples=len(active))
        # per-step load gauges on the event stream: the router's health
        # signals come from here, not from private scheduler state
        # (guarded — telemetry off must not pay the slot scan per step)
        if self.telemetry.enabled:
            g = self.gauges()
            self.telemetry.emit("serving", "step.gauges",
                                step=self._step_count, **g)
            self._metrics_step_gauges(g)
        # host-observed per-step token progress: a server saturated with
        # long generations must not be judged hung between completions
        self.resilience.serving_step_progress()
        for slot, req in active:
            tok = int(toks[slot])
            req.length += 1
            self._lengths[slot] = req.length
            self._last_tokens[slot] = tok
            finished = (tok == req.eos_token_id
                        or len(req.tokens) + 1 >= req.max_new_tokens
                        or req.length + 1 > self.max_len)
            req.emit_token(tok, finished)
            if finished:
                reason = ("eos" if tok == req.eos_token_id else
                          "max_tokens" if len(req.tokens)
                          >= req.max_new_tokens else "window")
                self._finish(req, reason, now, done)

    def _spec_step(self, done: List[Request]):
        """One speculative decode step: propose draft tokens on the host
        (``draft`` span), score every slot's pending token + proposals
        in ONE compiled verify dispatch, then commit the longest prefix
        the greedy oracle agreed with (``verify``/``spec_commit``
        spans). Emits 1 to ``k + 1`` tokens per active slot for the
        dispatch cost of one decode step; proposals right-pad to ``k``
        against the garbage block so the program shape never changes."""
        jnp = self._jnp
        if self._verify_fn is None:
            self._verify_fn = self._build_verify()
        k = self.spec_k
        active = [(s, r) for s, r in self.sched.running()
                  if s not in self._prefilling]
        tokens = np.zeros((self.config.decode_slots, k + 1), np.int32)
        tokens[:, 0] = self._last_tokens
        num_valid = np.ones((self.config.decode_slots,), np.int32)
        proposals: Dict[int, List[int]] = {}
        for slot, req in active:
            budget = self.sched.speculative_budget(req, k)
            props: List[int] = []
            if budget > 0:
                with self._req_span(req, "draft",
                                    proposer=self._proposer.name,
                                    budget=budget):
                    props = [int(t) for t in
                             self._proposer.propose(req, budget)][:budget]
            proposals[slot] = props
            if props:
                tokens[slot, 1:1 + len(props)] = props
                num_valid[slot] = 1 + len(props)
            # open the speculative ledger window over the verify write
            # extent [0, length + 1 + n_p). Admission's worst-case
            # reservation covers every window THIS engine can open, so
            # the grant must be empty — a future lazy-allocation policy
            # that takes real grants must also extend the slot's row of
            # self._tables first, or the verify writes would scatter
            # into the garbage block (the ledger stays general; the
            # fuzz drives its granting paths directly)
            granted = self.block_mgr.speculate(req.request_id,
                                               req.length + 1 + len(props))
            assert not granted, \
                "speculative grant without a device table update"
        t0 = self.clock()
        toks, self.cache = self._verify_fn(
            self.engine.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(self._tables), jnp.asarray(self._lengths),
            jnp.asarray(num_valid), self._next_rng())
        # the ONE designed host sync per decode step (same contract as
        # the non-speculative loop): verified tokens drive commit/finish
        toks = np.asarray(toks)  # graft-lint: disable=GL04
        now = self.clock()
        # chaos seam: a replica killed BETWEEN verify and commit has
        # emitted nothing from this window — host state is exactly the
        # pre-step state, so a retry or failover replays cleanly and
        # the router's exactly-once splice sees no speculative token
        raise_if("serving.spec_commit")
        self._step_count += 1
        self._spec_steps += 1
        self.telemetry.on_step_boundary(self._step_count,
                                        samples=len(active))
        if self.telemetry.enabled:
            g = self.gauges()
            self.telemetry.emit("serving", "step.gauges",
                                step=self._step_count, **g)
            self._metrics_step_gauges(g)
        self.resilience.serving_step_progress()
        for slot, req in active:
            props = proposals[slot]
            accepted = 0
            for p in props:
                if int(toks[slot, accepted]) == p:
                    accepted += 1
                else:
                    break
            # draft AND accepted counters land here, past the chaos
            # seam: a step killed between verify and commit counted
            # nothing, so its retry cannot double-count the window
            req.draft_tokens += len(props)
            self._window_draft_tokens += len(props)
            req.accepted_tokens += accepted
            self._window_accepted_tokens += accepted
            if self._tracer.enabled and req.trace is not None:
                # per-request view of the SHARED batched verify dispatch
                self._tracer.record_span(
                    "verify", req.trace["trace"], to_ns(t0), to_ns(now),
                    parent=req.trace.get("serve_id"),
                    proposed=len(props), accepted=accepted,
                    request_id=req.request_id)
            with self._req_span(req, "spec_commit", accepted=accepted):
                finished, reason = self._spec_commit(slot, req, toks[slot],
                                                     accepted)
            if finished:
                self._finish(req, reason, now, done)

    def _spec_commit(self, slot: int, req: Request, row, accepted: int):
        """Commit one verified row: emit the model's greedy tokens at
        rows ``0..accepted`` (the accepted drafts, then the correction —
        or, with everything accepted, the free bonus token) under the
        sequential finish semantics, so eos / token budget / model
        window stop the stream exactly where non-speculative decode
        would. The accepted extent folds into the block ledger in place
        (its KV was written by the verify dispatch); the rejected tail
        drops without copies — its rows sit past the committed length,
        masked out of every later attention window and overwritten by
        the next step's writes."""
        finished, reason = False, None
        for i in range(accepted + 1):
            tok = int(row[i])
            req.length += 1
            self._lengths[slot] = req.length
            self._last_tokens[slot] = tok
            finished = (tok == req.eos_token_id
                        or len(req.tokens) + 1 >= req.max_new_tokens
                        or req.length + 1 > self.max_len)
            req.emit_token(tok, finished)
            if finished:
                reason = ("eos" if tok == req.eos_token_id else
                          "max_tokens" if len(req.tokens)
                          >= req.max_new_tokens else "window")
                break
        # + 1: the pending last token's next write lands at req.length
        self.block_mgr.commit_speculative(req.request_id, req.length + 1)
        return finished, reason

    def _finish(self, req: Request, reason: str, now: float,
                done: List[Request]):
        if (self._tracer.enabled and req.trace is not None
                and req.first_token_ts):
            # one decode segment: first generated token -> finish (the
            # per-token cadence is the step loop's, not this request's)
            self._tracer.record_span(
                "decode", req.trace["trace"], to_ns(req.first_token_ts),
                to_ns(now), parent=req.trace.get("serve_id"),
                tokens=len(req.tokens), request_id=req.request_id)
        self.sched.finish(req, reason, now)
        # reset the slot's host-side row: an idle slot computes into the
        # garbage block until the next admission overwrites it
        if 0 <= req.slot < len(self._tables):
            self._tables[req.slot] = 0
            self._lengths[req.slot] = 0
            self._last_tokens[req.slot] = 0
            if self._keyed:
                self._clear_samp_slot(req.slot)
            self._prefilling.pop(req.slot, None)
            self._pf_tables.pop(req.slot, None)
            self._pf_pos.pop(req.slot, None)
        self._record(req, shed=False, began=True)
        done.append(req)
        self.finished.append(req)

    def _record(self, req: Request, shed: bool, began: bool):
        rec = req.record()
        self.records.append(rec)
        self.telemetry.emit(
            "serving", "request.shed" if shed else "request.finish",
            step=self._step_count, **rec)
        self._metrics_record(req, rec, shed)
        if self._tracer.enabled and req.trace is not None:
            # close the replica-side root span (opened at admission);
            # queue-head sheds that never won a slot carry no handle
            end_span(req.trace.pop("serve", None),
                     end_ns=to_ns(req.finish_ts or req.submit_ts),
                     state=req.state, reason=req.finish_reason,
                     tokens=len(req.tokens))
        if not began:
            return  # never bracketed: submit-time shed
        if shed:
            self.resilience.serving_request_abandon()
        else:
            self._finished_count += 1
            self.resilience.serving_heartbeat(self._finished_count)

    def _metrics_record(self, req: Request, rec: dict, shed: bool):
        """Per-terminal-request registry feed: latency histograms,
        outcome/token counters, prefix-cache and spec-decode window
        gauges. One no-op instrument call per line when metrics are
        disarmed."""
        m = self._metrics
        m.counter("ds_serving_requests_total", ("outcome",)).labels(
            outcome="shed" if shed else "finished").inc()
        if rec.get("ttft_ms") is not None:
            m.histogram("ds_serving_ttft_ms").observe(rec["ttft_ms"])
        if rec.get("queue_ms") is not None:
            m.histogram("ds_serving_queue_ms").observe(rec["queue_ms"])
        if shed:
            return
        m.counter("ds_serving_tokens_total").inc(rec.get("new_tokens") or 0)
        # tokens prove a first token landed — a fake clock legitimately
        # reading 0.0 at that moment must not drop the observation (the
        # timestamp fields are 0.0-sentinel by dataclass convention)
        if req.tokens:
            m.histogram("ds_serving_decode_ms").observe(
                1e3 * max(req.finish_ts - req.first_token_ts, 0.0))
        if self.prefix is not None:
            self._window_prompt_tokens += rec.get("prompt_len") or 0
            self._window_hit_tokens += rec.get("prefix_hit_tokens") or 0
            if self._window_prompt_tokens:
                m.gauge("ds_prefix_cache_hit_rate").set(round(
                    self._window_hit_tokens
                    / self._window_prompt_tokens, 4))
        if self._proposer is not None:
            drafts = rec.get("draft_tokens") or 0
            acc = rec.get("accepted_tokens") or 0
            if drafts:
                m.counter("ds_spec_draft_tokens_total").inc(drafts)
                m.counter("ds_spec_accepted_tokens_total").inc(acc)
            if self._window_draft_tokens:
                m.gauge("ds_spec_acceptance_rate").set(round(
                    self._window_accepted_tokens
                    / self._window_draft_tokens, 4))

    def _metrics_step_gauges(self, g: dict):
        """Per-decode-step pool/queue gauges from the SAME ``gauges()``
        payload the ``step.gauges`` event carries (one slot scan, two
        consumers — the surfaces cannot disagree)."""
        m = self._metrics
        m.gauge("ds_serving_queue_depth").set(g.get("queue_depth", 0))
        m.gauge("ds_serving_slots_busy").set(g.get("slots_busy", 0))
        m.gauge("ds_serving_slots_total").set(g.get("slots_total", 0))
        bm = self.block_mgr
        usable = max(1, bm.num_blocks - 1)   # garbage block excluded
        used = bm.num_allocated
        tier = m.gauge("ds_kv_pool_blocks", ("tier",))
        tier.labels(tier="free").set(bm.num_free)
        tier.labels(tier="cached").set(bm.num_cached)
        tier.labels(tier="used").set(used)
        m.gauge("ds_kv_pool_occupancy").set(round(used / usable, 4))
        committed = int(g.get("committed_tokens", 0))
        capacity = used * self.config.block_size
        m.gauge("ds_kv_pool_fragmentation").set(
            round(1.0 - committed / capacity, 4) if capacity else 0.0)

    # ------------------------------------------------------------------
    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Abandon one in-flight request (queued or mid-decode): its
        decode slot, KV blocks and token budget release immediately and
        it is recorded as shed with ``reason``. The multi-replica router
        calls this at failover so abandoned proxies never keep decoding
        on a replica that later recovers."""
        req = self.sched.cancel(request_id, reason, self.clock())
        if req is None:
            return False
        if 0 <= req.slot < len(self._tables):
            self._tables[req.slot] = 0
            self._lengths[req.slot] = 0
            self._last_tokens[req.slot] = 0
            if self._keyed:
                self._clear_samp_slot(req.slot)
            self._prefilling.pop(req.slot, None)
            self._pf_tables.pop(req.slot, None)
            self._pf_pos.pop(req.slot, None)
        self._record(req, shed=True, began=True)
        return True

    # ------------------------------------------------------------------
    # live KV-block migration seams (serving/migration.py orchestrates;
    # the router/fleet manager are the consumers — failover, drain and
    # fragmentation rebalance move committed state instead of replaying)
    def export_sequence(self, request_id: str) -> Optional[dict]:
        """Snapshot one decode-ready sequence's committed state for
        import on another replica: the request's identity and counters,
        its pending last token, and the per-block KV rows of every cache
        leaf (int8 side pools and their scales ride the same block
        indices), gathered on the block axis and split into per-TP-shard
        chunks along the head axis — the transfer unit PR 15's
        head-sharded pools define. Read-only on the source (an open
        speculative window is dropped first — it is uncommitted by
        definition), so a transfer that dies downstream leaves this
        replica able to keep decoding or to serve a replay. Returns None
        when the request is not migratable (unknown, queued, or still
        mid-prefill — those replay/resubmit cheaply)."""
        raise_if("serving.migration.export", detail=request_id)
        req = next((r for _, r in self.sched.running()
                    if r.request_id == request_id), None)
        if req is None or req.slot in self._prefilling or req.length <= 0:
            return None
        if self.block_mgr.speculating(request_id):
            self.block_mgr.drop_speculative(request_id)
        jax, jnp = self._jax, self._jnp
        bs = self.config.block_size
        covered = self.block_mgr.owned(request_id)[
            :blocks_for_tokens(req.length, bs)]
        tp = 1
        try:
            tp = int(dict(self.engine.mesh.shape).get("tp", 1))
        except Exception:
            tp = 1
        leaves, treedef = jax.tree_util.tree_flatten(self.cache)
        idx = jnp.asarray(np.asarray(covered, np.int32))
        rows, wire_bytes = [], 0
        for leaf in leaves:
            r = np.asarray(jnp.take(leaf, idx, axis=leaf.ndim - 4))
            h = r.ndim - 2
            if tp > 1 and r.shape[h] % tp == 0:
                chunks = [np.ascontiguousarray(c)
                          for c in np.split(r, tp, axis=h)]
            else:
                chunks = [r]
            wire_bytes += sum(c.nbytes for c in chunks)
            rows.append(chunks)
        return {
            "request_id": req.request_id,
            "prompt": list(req.prompt),
            "tokens": list(req.tokens),
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": int(req.eos_token_id),
            "deadline_ms": float(req.deadline_ms),
            "length": int(req.length),
            "last_token": int(self._last_tokens[req.slot]),
            "do_sample": bool(self.config.do_sample),
            # keyed per-request sampling state: the seed and knobs ARE
            # the whole sampler — position comes from length, so the
            # spliced slot resumes the stream bit-exactly with no
            # counter re-derivation (None for greedy requests)
            "sampling": ({
                "do_sample": True, "seed": int(req.seed or 0),
                "temperature": float(req.temperature
                                     if req.temperature is not None
                                     else 1.0),
                "top_k": int(req.top_k or 0),
                "top_p": float(req.top_p or 0.0),
            } if req.do_sample else None),
            "block_size": bs,
            "kv_cache_dtype": self.config.kv_cache_dtype or None,
            "tp_shards": tp,
            "blocks": len(covered),
            "rows": rows,
            "treedef": str(treedef),
            "wire_bytes": int(wire_bytes),
            "draft_tokens": int(req.draft_tokens),
            "accepted_tokens": int(req.accepted_tokens),
        }

    def import_sequence(self, export: Optional[dict],
                        deadline_ms: Optional[float] = None,
                        stream=None,
                        request_id: Optional[str] = None,
                        trace: Optional[dict] = None
                        ) -> Optional[Request]:
        """Splice an exported sequence into a free decode slot: allocate
        blocks, scatter the migrated rows onto this pool at exactly the
        rows every later ``paged_write_rows``-indexed program addresses
        through the rewritten table, seed the request's token/sampling
        counters, and resume decoding mid-stream — NO prefill program
        dispatch. Returns None when the export cannot land here (pool
        geometry/dtype/sampling mismatch, no free slot, duplicate id, or
        not enough blocks) so the caller can fall back to replay. The
        block table commit happens last: a fault before it (the
        ``serving.migration.commit`` chaos seam) releases every block
        this call allocated and leaves the scheduler untouched."""
        if export is None:
            return None
        rid = request_id or export["request_id"]
        samp = export.get("sampling")
        if (export["block_size"] != self.config.block_size
                or (export.get("kv_cache_dtype") or None)
                != (self.config.kv_cache_dtype or None)
                or bool(export["do_sample"]) != bool(self.config.do_sample)
                # a keyed sampled stream can only resume on a replica
                # whose decode program folds the same keys — a greedy
                # target would silently continue it greedily
                or (samp is not None and not self._keyed)
                or rid in self.sched._live_ids):
            return None
        slot = self.sched.free_slot()
        if slot is None:
            return None
        jax, jnp = self._jax, self._jnp
        mnt = int(export["max_new_tokens"]
                  or self.config.default_max_new_tokens)
        cost = len(export["prompt"]) + mnt
        if (cost > self.max_len or int(export["length"]) > cost
                or not self.block_mgr.can_allocate_shared(cost, (), None)):
            return None
        leaves, treedef = jax.tree_util.tree_flatten(self.cache)
        if str(treedef) != export["treedef"]:
            return None
        now = self.clock()
        req = Request(prompt=list(export["prompt"]),
                      max_new_tokens=mnt, request_id=rid,
                      eos_token_id=int(export["eos_token_id"]),
                      deadline_ms=(deadline_ms if deadline_ms is not None
                                   else export["deadline_ms"]),
                      stream=stream)
        if samp is not None:
            req.do_sample = True
            req.seed = int(samp["seed"])
            req.temperature = float(samp["temperature"])
            req.top_k = int(samp["top_k"])
            req.top_p = float(samp["top_p"])
        # delivered prefix rides along verbatim — seeded directly, NOT
        # via emit_token (the client already holds these tokens; the
        # stream fires only for tokens decoded after the splice)
        req.tokens = list(export["tokens"])
        req.draft_tokens = int(export.get("draft_tokens") or 0)
        req.accepted_tokens = int(export.get("accepted_tokens") or 0)
        req.submit_ts = now
        if req.tokens:
            req.first_token_ts = now
        # router-stamped trace context: the spliced request's replica
        # spans join the CLIENT's trace under the migration attempt
        req.trace = dict(trace) if trace is not None else None
        table = self.block_mgr.allocate(rid, cost)
        try:
            B = int(export["blocks"])
            if B:
                rows_leaves = []
                for chunks in export["rows"]:
                    r = (chunks[0] if len(chunks) == 1 else np.concatenate(
                        chunks, axis=chunks[0].ndim - 2))
                    rows_leaves.append(jnp.asarray(r))
                rows = jax.tree_util.tree_unflatten(treedef, rows_leaves)
                if B not in self._migrate_fns:
                    self._migrate_fns[B] = self._build_migrate(B)
                dst = jnp.asarray(np.asarray(table[:B], np.int32))
                self.cache = self._migrate_fns[B](self.cache, rows, dst)
            raise_if("serving.migration.commit", detail=rid)
            self.sched.splice(req, slot, now)
        except Exception:
            # rows already scattered are stale bytes in blocks the pool
            # no longer maps — harmless; the scheduler never saw us
            self.block_mgr.release(rid)
            raise
        req.length = int(export["length"])
        self._tables[slot] = table
        self._lengths[slot] = req.length
        self._last_tokens[slot] = int(export["last_token"])
        if self._keyed:
            self._set_samp_slot(slot, req)
        self.resilience.serving_request_begin()
        self.telemetry.emit("serving", "request.migrated_in",
                            step=self._step_count, request_id=rid,
                            blocks=int(export["blocks"]),
                            wire_bytes=int(export["wire_bytes"]),
                            length=req.length)
        return req

    def migrate_out(self, request_id: str) -> bool:
        """Detach a migrated-away request from this replica: free its
        slot, blocks and token budget WITHOUT a shed record — the
        request is still live, on another replica, in the same client
        trace. Call only after the target committed its import."""
        now = self.clock()
        req = self.sched.migrate_out(request_id, now)
        if req is None:
            return False
        if 0 <= req.slot < len(self._tables):
            self._tables[req.slot] = 0
            self._lengths[req.slot] = 0
            self._last_tokens[req.slot] = 0
            if self._keyed:
                self._clear_samp_slot(req.slot)
            self._prefilling.pop(req.slot, None)
            self._pf_tables.pop(req.slot, None)
            self._pf_pos.pop(req.slot, None)
        if self._tracer.enabled and req.trace is not None:
            end_span(req.trace.pop("serve", None), end_ns=to_ns(now),
                     state="migrated", tokens=len(req.tokens))
        self.resilience.serving_request_abandon()
        self.telemetry.emit("serving", "request.migrated_out",
                            step=self._step_count, request_id=request_id,
                            tokens=len(req.tokens))
        return True

    def gauges(self) -> dict:
        """Instantaneous load gauges (queue depth, busy slots, free
        blocks): the payload of the per-step ``serving``/``step.gauges``
        telemetry event and the numbers the multi-replica router routes
        by — one public surface, no private-state reach-ins."""
        g = {**self.sched.gauges(), "free_blocks": self.block_mgr.num_free}
        if self.prefix is not None:
            g["cached_blocks"] = self.block_mgr.num_cached
        # decode-side fragmentation (same formula as the PR 14
        # ds_kv_pool_fragmentation gauge): the rebalance trigger
        committed = int(g.get("committed_tokens", 0))
        capacity = self.block_mgr.num_allocated * self.config.block_size
        g["kv_fragmentation"] = (round(1.0 - committed / capacity, 4)
                                 if capacity else 0.0)
        return g

    @property
    def pending(self) -> bool:
        return self.sched.pending

    def drain(self, max_steps: Optional[int] = None) -> List[Request]:
        """Step until queue and slots are empty (or ``max_steps``);
        returns every request finished during the drain."""
        out: List[Request] = []
        steps = 0
        while self.pending and (max_steps is None or steps < max_steps):
            out.extend(self.step())
            steps += 1
        return out

    def generate_batch(self, prompts, max_new_tokens: int = 0, **kwargs):
        """Convenience: submit every prompt, drain, return each request's
        generated tokens in submit order (None for shed requests)."""
        reqs = [self.submit(p, max_new_tokens=max_new_tokens, **kwargs)
                for p in prompts]
        self.drain()
        return [r.tokens if r.state == FINISHED else None for r in reqs]

    def reset_stats(self):
        """Clear the per-request records and scheduler counters (a bench
        epoch boundary between warmup and the measured window); in-flight
        requests and the cache pool are untouched."""
        self.records.clear()
        self.finished.clear()
        self._spec_steps = 0
        self._window_draft_tokens = 0
        self._window_accepted_tokens = 0
        self._window_prompt_tokens = 0
        self._window_hit_tokens = 0
        self.sched.reset_stats()

    def stats(self) -> dict:
        """Aggregate serving metrics (the bench's ``*_serving`` series)."""
        ttfts = [r["ttft_ms"] for r in self.records
                 if r.get("ttft_ms") is not None]
        rates = [r["tokens_per_sec"] for r in self.records
                 if r.get("tokens_per_sec") is not None]
        prefix_stats = None
        if self.prefix is not None:
            finished = [r for r in self.records if r["state"] != "shed"]
            prompt_toks = sum(r["prompt_len"] for r in finished)
            hit_toks = sum(r.get("prefix_hit_tokens", 0) for r in finished)
            prefix_stats = {
                **self.prefix.stats,
                "cached_blocks": self.block_mgr.num_cached,
                "evictions": self.block_mgr.evictions,
                "window_hit_rate": round(hit_toks / prompt_toks, 4)
                if prompt_toks else 0.0,
            }
        spec_stats = None
        if self._proposer is not None:
            # window counters, not the bounded records deque: a long
            # run past the deque's maxlen must not decay the ratios
            drafts = self._window_draft_tokens
            acc = self._window_accepted_tokens
            spec_stats = {
                "proposer": self._proposer.name,
                "num_speculative_tokens": self.spec_k,
                "draft_tokens": drafts,
                "accepted_tokens": acc,
                "acceptance_rate": round(acc / drafts, 4)
                if drafts else None,
                # aggregate extra tokens ONE verify dispatch bought,
                # over the stats window — the headline speculation win
                "accepted_tokens_per_step": round(acc / self._spec_steps, 4)
                if self._spec_steps else None,
            }
        s = self.sched.stats
        total = max(1, s["submitted"])
        return {
            "prefix_cache": prefix_stats,
            "speculative": spec_stats,
            "finished": s["finished"], "shed": s["shed"],
            "shed_reasons": dict(s["shed_reasons"]),
            "shed_rate": round(s["shed"] / total, 4),
            "migrated_in": s["migrated_in"],
            "migrated_out": s["migrated_out"],
            "queue_peak": s["queue_peak"],
            "decode_steps": self._step_count,
            "ttft_ms_p50": round(float(np.percentile(ttfts, 50)), 3)
            if ttfts else None,
            "ttft_ms_p95": round(float(np.percentile(ttfts, 95)), 3)
            if ttfts else None,
            "tokens_per_sec_mean": round(float(np.mean(rates)), 2)
            if rates else None,
        }

    def destroy(self):
        """Drop compiled programs and the cache pool; destroys the wrapped
        engine only when this ServingEngine constructed it."""
        self._prefill_fns.clear()
        self._chunk_fns.clear()
        self._decode_fn = None
        self._cow_fn = None
        self._migrate_fns.clear()
        self._verify_fn = None
        self.cache = None
        if self._owns_engine:
            self.engine.destroy()
