"""Per-replica capacity model: latency-vs-load curves and sizing.

Pure host-side (no jax, no clock reads). Fits two things from observed
serving evidence, bucketed by per-replica load:

- **TTFT / queue-wait p95 curves** — one fixed-bucket
  :class:`~deepspeed_tpu.telemetry.metrics.Histogram` per load bucket
  (constant memory over any run length; models from two runs or two
  replicas :meth:`merge` exactly, the PR 10 histogram contract);
- **sustainable token throughput** — tokens generated per simulated
  second at each load level, so "how many replicas does this offered
  load need at this SLO" has a measured answer:
  :meth:`fleet_size_for`.

*Load* is the replica's queue-pressure fraction — ``(slots_busy +
queue_depth) / slots_total`` from the public ``gauges()`` payload — so
1.0 means every decode slot busy and nothing queued, 2.0 means a full
queue one slot-generation deep, and so on. Buckets cover [0, max_load)
plus an overflow bucket.

Evidence arrives two ways: live feeding (:meth:`observe` /
:meth:`observe_gauges`, the trace-replay path) or a telemetry event
stream (:meth:`fit_events`: per-step load from ``serving``/
``step.gauges`` events, latencies from ``serving``/``request.finish``
records and ``span`` ``queue`` legs with step attribution).
"""

import math
from typing import Dict, Iterable, List, Optional

from deepspeed_tpu.telemetry.metrics import MS_BOUNDS, Histogram

# millisecond-scale geometric ladder: 2**-6 .. 2**25 ms (~15 us .. ~9 h)
# — the telemetry-wide shared ladder, so the metric registry's latency
# histograms merge exactly into these curves (fit_snapshot)
_MS_BOUNDS = MS_BOUNDS


def _gauge_value(snapshot: Dict, name: str) -> float:
    """First unlabeled-series value of a gauge family in a registry
    snapshot (0.0 when absent)."""
    for row in (snapshot.get(name) or {}).get("series", []):
        if row.get("value") is not None:
            return float(row["value"])
    return 0.0


def _histogram_from_row(row: Dict) -> Optional[Histogram]:
    """Reconstruct a mergeable Histogram from one snapshot series row
    (bounds + per-bucket counts + count/sum/min/max)."""
    bounds = row.get("bounds")
    counts = row.get("counts")
    if not bounds or counts is None:
        return None
    h = Histogram(bounds)
    if len(counts) != len(h.counts):
        return None
    h.counts = [int(c) for c in counts]
    h.count = int(row.get("count") or sum(h.counts))
    h.total = float(row.get("sum") or 0.0)
    h.min = row.get("min")
    h.max = row.get("max")
    if h.count and h.max is None:
        # parsed-scrape rows carry no extremes: the top non-empty
        # bucket's bound is the honest stand-in (percentile clamps on
        # max, which must not be None while counts exist)
        top = max(i for i, c in enumerate(h.counts) if c)
        h.max = float(bounds[min(top, len(bounds) - 1)])
        h.min = 0.0
    return h


class CapacityModel:
    def __init__(self, n_buckets: int = 8, max_load: float = 2.0):
        if n_buckets <= 0 or max_load <= 0:
            raise ValueError("CapacityModel needs n_buckets > 0 and "
                             f"max_load > 0, got {n_buckets}/{max_load}")
        self.n_buckets = int(n_buckets)
        self.max_load = float(max_load)
        # n_buckets over [0, max_load) + one overflow bucket
        self._ttft: List[Histogram] = [Histogram(_MS_BOUNDS)
                                       for _ in range(self.n_buckets + 1)]
        self._queue: List[Histogram] = [Histogram(_MS_BOUNDS)
                                        for _ in range(self.n_buckets + 1)]
        self._tokens: List[float] = [0.0] * (self.n_buckets + 1)
        self._secs: List[float] = [0.0] * (self.n_buckets + 1)

    # ------------------------------------------------------------------
    def bucket(self, load: float) -> int:
        i = int(max(0.0, float(load)) / self.max_load * self.n_buckets)
        return min(i, self.n_buckets)

    def bucket_load(self, i: int) -> float:
        """Representative (midpoint) load of bucket ``i``."""
        width = self.max_load / self.n_buckets
        return (i + 0.5) * width

    @staticmethod
    def load_of(gauges: dict) -> float:
        """The load definition, from a public ``gauges()`` payload."""
        slots = max(1, int(gauges.get("slots_total", 0) or 1))
        busy = int(gauges.get("slots_busy", 0))
        depth = int(gauges.get("queue_depth", 0))
        return (busy + depth) / slots

    # ------------------------------------------------------------------
    # evidence
    def observe(self, load: float, *, ttft_ms: Optional[float] = None,
                queue_ms: Optional[float] = None, tokens: float = 0.0,
                secs: float = 0.0) -> None:
        i = self.bucket(load)
        if ttft_ms is not None:
            self._ttft[i].observe(ttft_ms)
        if queue_ms is not None:
            self._queue[i].observe(queue_ms)
        self._tokens[i] += float(tokens)
        self._secs[i] += float(secs)

    def observe_gauges(self, gauges: dict, *, tokens: float = 0.0,
                       secs: float = 0.0) -> float:
        """Per-step feeding from a live replica: attributes this step's
        generated ``tokens`` over ``secs`` simulated seconds to the load
        the gauges show. Returns the load (callers often want it)."""
        load = self.load_of(gauges)
        self.observe(load, tokens=tokens, secs=secs)
        return load

    def fit_events(self, events: Iterable[dict]) -> int:
        """Fit from a telemetry event stream (the offline path): builds
        a step -> load map from ``serving``/``step.gauges``, then
        attributes ``request.finish`` TTFT/queue latencies and token
        throughput — and ``span`` ``queue`` legs carrying a ``step``
        attribute — to the load at their step. Returns the number of
        observations consumed."""
        events = list(events)
        load_at: Dict[int, float] = {}
        for e in events:
            if e.get("kind") == "serving" and e.get("name") == "step.gauges" \
                    and e.get("step") is not None:
                load_at[int(e["step"])] = self.load_of(e.get("data") or {})
        if not load_at:
            return 0
        steps = sorted(load_at)

        def nearest(step):
            if step in load_at:
                return load_at[step]
            prior = [s for s in steps if s <= step]
            return load_at[prior[-1] if prior else steps[0]]

        used = 0
        for e in events:
            kind, name, data = e.get("kind"), e.get("name"), \
                e.get("data") or {}
            step = e.get("step")
            if kind == "serving" and name == "request.finish" \
                    and step is not None:
                load = nearest(int(step))
                tps = data.get("tokens_per_sec")
                toks = data.get("new_tokens") or 0
                self.observe(
                    load, ttft_ms=data.get("ttft_ms"),
                    queue_ms=data.get("queue_ms"), tokens=toks,
                    secs=(toks / tps) if (tps and toks) else 0.0)
                used += 1
            elif kind == "span" and name == "queue" \
                    and data.get("step") is not None:
                load = nearest(int(data["step"]))
                dur_ms = max(0, int(data.get("end_ns", 0))
                             - int(data.get("start_ns", 0))) / 1e6
                self.observe(load, queue_ms=dur_ms)
                used += 1
        return used

    def fit_snapshot(self, snapshot: Dict, *,
                     load: Optional[float] = None) -> int:
        """Fit from a metric-registry snapshot (the live metrics plane's
        format: ``MetricRegistry.snapshot()``, a flight-recorder
        snapshot ring entry, or a parsed scrape) instead of raw events.
        The registry's ``ds_serving_ttft_ms``/``ds_serving_queue_ms``
        histograms share the capacity ladder (``telemetry.metrics.
        MS_BOUNDS``) so their counts merge EXACTLY into the load
        bucket's curve. ``load`` defaults to the snapshot's own queue/
        slot gauges. Feed each snapshot once (the registry is
        cumulative — delta successive snapshots externally, or fit the
        final one). Returns observations consumed."""
        if load is None:
            load = self.load_of({
                "queue_depth": _gauge_value(snapshot,
                                            "ds_serving_queue_depth"),
                "slots_busy": _gauge_value(snapshot,
                                           "ds_serving_slots_busy"),
                "slots_total": _gauge_value(snapshot,
                                            "ds_serving_slots_total"),
            })
        i = self.bucket(load)
        used = 0
        for metric, target in (("ds_serving_ttft_ms", self._ttft),
                               ("ds_serving_queue_ms", self._queue)):
            for row in (snapshot.get(metric) or {}).get("series", []):
                if not row.get("count"):
                    continue
                h = _histogram_from_row(row)
                if h is None or h.bounds != target[i].bounds:
                    continue  # foreign ladder: no exact merge exists
                target[i].merge(h)
                used += h.count
        return used

    def merge(self, other: "CapacityModel") -> "CapacityModel":
        if (self.n_buckets, self.max_load) != (other.n_buckets,
                                               other.max_load):
            raise ValueError("cannot merge capacity models with different "
                             "bucket ladders")
        for i in range(self.n_buckets + 1):
            self._ttft[i].merge(other._ttft[i])
            self._queue[i].merge(other._queue[i])
            self._tokens[i] += other._tokens[i]
            self._secs[i] += other._secs[i]
        return self

    # ------------------------------------------------------------------
    # curves
    def ttft_p95_at(self, load: float) -> Optional[float]:
        return self._ttft[self.bucket(load)].percentile(95)

    def queue_p95_at(self, load: float) -> Optional[float]:
        return self._queue[self.bucket(load)].percentile(95)

    def throughput_at(self, load: float) -> Optional[float]:
        """Tokens per simulated second observed at ``load`` (None with
        no time attributed to that bucket)."""
        i = self.bucket(load)
        return self._tokens[i] / self._secs[i] if self._secs[i] > 0 \
            else None

    def curve(self) -> List[dict]:
        """The fitted table, one row per bucket with data — what the
        bench series and report render."""
        out = []
        for i in range(self.n_buckets + 1):
            if not (self._ttft[i].count or self._queue[i].count
                    or self._secs[i] > 0):
                continue
            out.append({
                "load": round(self.bucket_load(i), 3)
                if i < self.n_buckets else f">={self.max_load}",
                "ttft_ms_p95": self._ttft[i].percentile(95),
                "queue_ms_p95": self._queue[i].percentile(95),
                "tokens_per_sec": round(self._tokens[i] / self._secs[i], 2)
                if self._secs[i] > 0 else None,
                "requests": self._ttft[i].count,
            })
        return out

    # ------------------------------------------------------------------
    # sizing
    def sustainable_tokens_per_sec(
            self, ttft_p95_ms: float = 0.0,
            queue_p95_ms: float = 0.0) -> Optional[float]:
        """Highest per-replica throughput observed at any load level
        whose latency percentiles meet the SLO (0 = that target is
        unconstrained). None when no bucket has both throughput data and
        an SLO-clean latency reading."""
        best = None
        for i in range(self.n_buckets + 1):
            if self._secs[i] <= 0:
                continue
            ttft = self._ttft[i].percentile(95)
            queue = self._queue[i].percentile(95)
            if ttft_p95_ms > 0 and (ttft is None or ttft > ttft_p95_ms):
                continue
            if queue_p95_ms > 0 and (queue is None or queue > queue_p95_ms):
                continue
            rate = self._tokens[i] / self._secs[i]
            if best is None or rate > best:
                best = rate
        return best

    def fleet_size_for(self, load_tokens_per_sec: float, slo: dict,
                       *, min_size: int = 1,
                       max_size: Optional[int] = None) -> int:
        """Smallest fleet that serves ``load_tokens_per_sec`` within the
        SLO (``{"ttft_p95_ms": ..., "queue_p95_ms": ...}``; omitted keys
        are unconstrained), from the fitted per-replica sustainable
        rate. With no usable evidence the honest answer is the floor —
        the caller sizes from budget burn instead."""
        slo = slo or {}
        per = self.sustainable_tokens_per_sec(
            float(slo.get("ttft_p95_ms") or 0.0),
            float(slo.get("queue_p95_ms") or 0.0))
        if not per or per <= 0:
            n = min_size
        else:
            n = max(min_size, math.ceil(float(load_tokens_per_sec) / per))
        return min(n, max_size) if max_size else n
