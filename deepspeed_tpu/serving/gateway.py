"""Multi-tenant HTTP/SSE front door for the serving stack.

:class:`ServingGateway` wraps ANY ``submit()/step()/drain()`` backend —
a ``FleetManager``, a ``ReplicaRouter`` or a bare ``ServingEngine`` —
behind a stdlib :class:`http.server.ThreadingHTTPServer` (the PR 14
metrics-server pattern: daemon threads, ephemeral ``port=0``,
deterministic ``close()``):

- ``POST /v1/generate`` — JSON in, SSE token stream out (``event:
  token`` per generated token, a terminal ``event: done`` carrying the
  backend record, or a typed ``event: error`` when the request was shed
  mid-stream); ``"stream": false`` selects a non-streaming JSON reply.
- ``GET /healthz`` — backend liveness + gauges.
- ``GET /metrics`` — the existing exposition, mounted on the same port.

Tenancy rides ``serving.gateway``: API-key identity, token-bucket rate
limits and inflight quotas (``tenancy.py``), SLO classes mapped onto the
scheduler's priority floor and deadline defaults. Overload answers 429/
503 with ``Retry-After`` instead of hanging sockets. Delivery is
decoupled from the step loop by a BOUNDED per-connection send queue: the
stream callback (step thread) never blocks — a slow reader overflows its
own queue and sheds that request only, via the backend ``cancel()``
seam, drained at the next :meth:`ServingGateway.step`.

Pure host code: never imports jax (GL01) and reads only the injected
clock (GL07) — the trace-replay harness runs the whole front door on
simulated time, bit-deterministically.
"""

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.serving.config import GatewayConfig
from deepspeed_tpu.serving.tenancy import Tenant, TenantTable
from deepspeed_tpu.telemetry.registry import NULL_REGISTRY
from deepspeed_tpu.telemetry.prom import CONTENT_TYPE
from deepspeed_tpu.telemetry.tracing import (NULL_TRACER, end_span, span_id,
                                             to_ns, trace_ctx)

GENERATE_ROUTE = "/v1/generate"

# admission reason -> HTTP status
_REASON_STATUS = {
    "auth": 401, "forbidden": 403, "bad_request": 400, "too_large": 413,
    "sampling_invalid": 400,
    "rate": 429, "tokens": 429, "inflight": 429, "overload": 503,
    "backend_shed": 503,
}


def _validate_sampling(body: dict) -> Optional[str]:
    """Range-check the keyed-sampling fields of a parsed request body.
    Returns a reject reason or None. Checked at the DOOR so an
    out-of-range temperature answers a typed 400, not a backend shed
    deep in the step loop."""
    if "do_sample" in body and not isinstance(body["do_sample"], bool):
        return "sampling_invalid"
    seed = body.get("seed")
    if seed is not None and (not isinstance(seed, int)
                             or isinstance(seed, bool) or seed < 0):
        return "sampling_invalid"
    temp = body.get("temperature")
    if temp is not None and (not isinstance(temp, (int, float))
                             or isinstance(temp, bool) or temp <= 0):
        return "sampling_invalid"
    top_k = body.get("top_k")
    if top_k is not None and (not isinstance(top_k, int)
                              or isinstance(top_k, bool) or top_k < 0):
        return "sampling_invalid"
    top_p = body.get("top_p")
    if top_p is not None and (not isinstance(top_p, (int, float))
                              or isinstance(top_p, bool)
                              or not 0.0 <= top_p <= 1.0):
        return "sampling_invalid"
    return None


class _NullTelemetry:
    enabled = False

    def emit(self, *a, **k):
        pass


class _Stream:
    """Per-request delivery state shared between the step thread (the
    stream callback producing) and the handler thread (consuming)."""

    def __init__(self, request_id: str, maxsize: int):
        self.request_id = request_id
        self.q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self.first_ts: Optional[float] = None
        self.tokens = 0
        self.overflow = False
        self.closed = False


class ServingGateway:
    """The HTTP/SSE front door. Construct over a backend, ``start()``,
    then drive the backend loop through :meth:`step`/:meth:`drain` (or
    set ``serving.gateway.pump`` to own a daemon step loop)."""

    def __init__(self, backend, config=None, *, telemetry=None,
                 clock=time.monotonic):
        if isinstance(config, GatewayConfig):
            self.config = config
        else:
            self.config = GatewayConfig(**(config or {}))
        self.backend = backend
        self.clock = clock
        self.telemetry = (telemetry
                          or getattr(backend, "telemetry", None)
                          or _NullTelemetry())
        self._metrics = getattr(self.telemetry, "metrics", None) \
            or NULL_REGISTRY
        self._tracer = getattr(self.telemetry, "tracer", None) \
            or NULL_TRACER
        self.tenants = TenantTable(self.config, clock=clock)
        self._routerlike = (hasattr(backend, "overload")
                            or hasattr(backend, "router"))
        self._lock = threading.Lock()
        self._streams: Dict[str, _Stream] = {}
        self._cancels: List[Tuple[str, str]] = []
        self._count = 0
        self._step_count = 0
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._pump_thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._running = False
        # per-tenant counters for stats()/bench (metrics may be off)
        self._counts: Dict[str, Dict[str, int]] = {}

    # ------------------------------------------------------------------
    # lifecycle
    def start(self) -> "ServingGateway":
        if self._server is not None:
            return self
        server = ThreadingHTTPServer((self.config.host, self.config.port),
                                     _Handler)
        server.daemon_threads = True
        server.gateway = self
        self._server = server
        self._running = True
        self._thread = threading.Thread(target=server.serve_forever,
                                        name="ds-gateway", daemon=True)
        self._thread.start()
        if self.config.pump:
            self._pump_thread = threading.Thread(
                target=self._pump, name="ds-gateway-pump", daemon=True)
            self._pump_thread.start()
        return self

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    @property
    def url(self) -> str:
        return f"http://{self.config.host}:{self.port}"

    def close(self):
        self._running = False
        self._wake.set()
        if self._pump_thread is not None:
            self._pump_thread.join(2.0)
            self._pump_thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    def destroy(self):
        self.close()
        backend_destroy = getattr(self.backend, "destroy", None)
        if backend_destroy is not None:
            backend_destroy()

    def submit(self, prompt, **kwargs):
        """Direct Python-path passthrough — the backend surface stays
        reachable behind the gateway (no quotas, no HTTP)."""
        return self.backend.submit(prompt, **kwargs)

    # ------------------------------------------------------------------
    # step loop
    def step(self):
        """Drain deferred cancels (slow readers / disconnects, queued by
        handler threads where touching the scheduler would race the step
        loop), then advance the backend one step."""
        self._drain_cancels()
        self._step_count += 1
        return self.backend.step()

    def drain(self, max_steps: Optional[int] = None):
        self._drain_cancels()
        return self.backend.drain(max_steps)

    @property
    def pending(self) -> bool:
        return bool(getattr(self.backend, "pending", False))

    def _pump(self):
        while self._running:
            self._wake.wait(self.config.poll_secs)
            self._wake.clear()
            while self._running and (self.pending or self._cancels):
                self.step()

    def _drain_cancels(self):
        with self._lock:
            pending, self._cancels = self._cancels, []
        cancel = getattr(self.backend, "cancel", None)
        for request_id, reason in pending:
            if cancel is not None:
                cancel(request_id, reason)

    def _request_cancel(self, request_id: str, reason: str):
        with self._lock:
            self._cancels.append((request_id, reason))
        self._wake.set()

    # ------------------------------------------------------------------
    # accounting
    def _emit(self, name: str, **data):
        if getattr(self.telemetry, "enabled", False):
            self.telemetry.emit("gateway", name, step=self._step_count,
                                **data)

    def _bump(self, tenant: str, key: str, n: int = 1):
        with self._lock:
            row = self._counts.setdefault(tenant, {})
            row[key] = row.get(key, 0) + n

    def _reject(self, tenant_name: str, reason: str, status: int,
                trace=None):
        self._bump(tenant_name, "rejected")
        self._bump(tenant_name, f"http_{status}")
        self._metrics.counter("ds_gateway_requests_total",
                              labels=("tenant", "outcome")) \
            .labels(tenant=tenant_name, outcome="rejected").inc()
        self._metrics.counter("ds_gateway_rejects_total",
                              labels=("tenant", "reason")) \
            .labels(tenant=tenant_name, reason=reason).inc()
        self._emit("request.rejected", tenant=tenant_name, reason=reason,
                   status=status)
        if trace is not None:
            tid, root = trace
            now_ns = to_ns(self.clock())
            self._tracer.record_span("shed", tid, now_ns, now_ns,
                                     parent=span_id(root), reason=reason,
                                     tenant=tenant_name)
            end_span(root, end_ns=now_ns, status=status)

    def _finish(self, tenant: Tenant, stream: _Stream, outcome: str,
                reason: str = "", ttft_ms: Optional[float] = None,
                trace=None, status: int = 200):
        """Exactly-once terminal accounting for an admitted request."""
        if stream.closed:
            return
        stream.closed = True
        with self._lock:
            self._streams.pop(stream.request_id, None)
        tenant.release()
        shed = outcome != "ok"
        tenant.record_outcome(shed, ttft_ms)
        self._bump(tenant.name, outcome)
        if not shed:
            self._bump(tenant.name, "finished")
        self._metrics.counter("ds_gateway_requests_total",
                              labels=("tenant", "outcome")) \
            .labels(tenant=tenant.name, outcome=outcome).inc()
        if stream.tokens:
            self._metrics.counter("ds_gateway_tokens_total",
                                  labels=("tenant",)) \
                .labels(tenant=tenant.name).inc(stream.tokens)
        if shed and reason:
            self._metrics.counter("ds_gateway_stream_sheds_total",
                                  labels=("tenant", "cause")) \
                .labels(tenant=tenant.name, cause=reason).inc()
        self._gauge_tenant(tenant)
        self._emit("request.finished", tenant=tenant.name, outcome=outcome,
                   reason=reason, request_id=stream.request_id,
                   tokens=stream.tokens, ttft_ms=ttft_ms,
                   budget_remaining=round(tenant.budget_remaining(), 6))
        if trace is not None:
            tid, root = trace
            end_span(root, end_ns=to_ns(self.clock()), status=status,
                     outcome=outcome, tokens=stream.tokens)

    def _gauge_tenant(self, tenant: Tenant):
        self._metrics.gauge("ds_gateway_inflight", labels=("tenant",)) \
            .labels(tenant=tenant.name).set(tenant.inflight)
        self._metrics.gauge("ds_gateway_budget_remaining",
                            labels=("tenant",)) \
            .labels(tenant=tenant.name).set(tenant.budget_remaining())

    def stats(self) -> dict:
        """Per-tenant gateway counters + budget remaining (host-side,
        independent of the metrics plane being armed)."""
        with self._lock:
            counts = {t: dict(row) for t, row in self._counts.items()}
        out = {"tenants": {}}
        for tenant in self.tenants.tenants:
            row = counts.get(tenant.name, {})
            row["inflight"] = tenant.inflight
            row["budget_remaining"] = round(tenant.budget_remaining(), 6)
            row["slo_class"] = tenant.slo_class
            out["tenants"][tenant.name] = row
        return out

    # ------------------------------------------------------------------
    # admission (handler thread)
    def _next_id(self) -> str:
        with self._lock:
            self._count += 1
            return f"gw-{self._count}"

    def authenticate(self, api_key: Optional[str]):
        """(tenant, error reason) — exactly one side is set."""
        if self.tenants.open:
            return self.tenants.resolve(None), ""
        if not api_key:
            return None, "auth"
        tenant = self.tenants.resolve(api_key)
        if tenant is None:
            return None, "forbidden"
        return tenant, ""

    def admit(self, tenant: Tenant, body: dict):
        """Quota + backend admission for a parsed, authenticated request.
        Returns ``(handle, stream, trace, retry_after, reason)`` —
        ``handle`` is None when rejected."""
        t0 = self.clock()
        trace = None
        if self._tracer.enabled and tenant.sample_trace():
            tid = self._tracer.new_trace(hint=tenant.name)
            root = self._tracer.begin("gateway", tid, start_ns=to_ns(t0),
                                      tenant=tenant.name,
                                      route=GENERATE_ROUTE)
            trace = (tid, root)
            self._tracer.record_span("auth", tid, to_ns(t0), to_ns(t0),
                                     parent=span_id(root),
                                     tenant=tenant.name)
        max_new = int(body.get("max_new_tokens", 0) or 0)
        overload = getattr(self.backend, "overload", None)
        threshold = self.config.overload_reject_threshold
        if (threshold > 0 and overload is not None
                and overload() >= threshold):
            self._reject(tenant.name, "overload", 503, trace)
            return None, None, None, self.config.retry_after_secs, \
                "overload"
        reason, wait = tenant.admit(est_tokens=float(max_new))
        if trace is not None:
            self._tracer.record_span("quota", trace[0], to_ns(t0),
                                     to_ns(self.clock()),
                                     parent=span_id(trace[1]),
                                     tenant=tenant.name,
                                     outcome=reason or "ok")
        if reason:
            self._reject(tenant.name, reason, 429, trace)
            return None, None, None, \
                max(wait, self.config.retry_after_secs), reason
        request_id = str(body.get("request_id") or self._next_id())
        stream = _Stream(request_id, self.config.send_queue_tokens)
        kwargs: Dict[str, Any] = {
            "max_new_tokens": max_new,
            "request_id": request_id,
            "deadline_ms": float(body.get("deadline_ms")
                                 or tenant.deadline_ms),
            "stream": self._make_stream_cb(tenant, stream),
        }
        if "eos_token_id" in body:
            kwargs["eos_token_id"] = int(body["eos_token_id"])
        if body.get("do_sample"):
            # keyed sampling rides through verbatim: the seed IS the
            # reproducibility contract, so the gateway must not rewrite
            # or default it — the serving config owns knob defaults
            kwargs["do_sample"] = True
            if body.get("seed") is not None:
                kwargs["seed"] = int(body["seed"])
            if body.get("temperature") is not None:
                kwargs["temperature"] = float(body["temperature"])
            if body.get("top_k") is not None:
                kwargs["top_k"] = int(body["top_k"])
            if body.get("top_p") is not None:
                kwargs["top_p"] = float(body["top_p"])
        if self._routerlike:
            kwargs["priority"] = tenant.priority
        elif trace is not None:
            # bare-engine backend: its serve/decode spans join the
            # gateway trace (router backends manage their own trace)
            kwargs["trace"] = trace_ctx(trace[0],
                                        parent=span_id(trace[1]))
        with self._lock:
            self._streams[request_id] = stream
        handle = self.backend.submit(body["prompt"], **kwargs)
        if getattr(handle, "state", "") == "shed":
            # backend admission control said no (queue full / duplicate
            # id / inflight-token cap): surface it as 503, not a hang
            self._finish(tenant, stream, "shed",
                         reason=getattr(handle, "finish_reason", "")
                         or "backend_shed", trace=trace, status=503)
            return None, None, None, self.config.retry_after_secs, \
                "backend_shed"
        self._gauge_tenant(tenant)
        self._bump(tenant.name, "admitted")
        if body.get("do_sample"):
            # per-tenant replay breakdown: how much of this tenant's
            # admitted traffic is keyed-sampled (stats()/bench read it)
            self._bump(tenant.name, "sampled")
        self._wake.set()
        return handle, stream, trace, 0.0, ""

    def _make_stream_cb(self, tenant: Tenant, stream: _Stream):
        def on_token(req, token: int, done: bool):
            if stream.closed or stream.overflow:
                return
            if stream.first_ts is None:
                # step-thread clock read: deterministic under the
                # replay harness' simulated time
                stream.first_ts = self.clock()
            try:
                stream.q.put_nowait(("token", int(token)))
                stream.tokens += 1
                if done:
                    stream.q.put_nowait(("done",))
            except queue.Full:
                # slow reader: shed THIS request only — never block the
                # step loop. The cancel drains at the next gateway step.
                stream.overflow = True
                self._request_cancel(stream.request_id, "slow_reader")
        return on_token

    def observe_ttft(self, tenant: Tenant, stream: _Stream,
                     submit_ts: float) -> Optional[float]:
        if stream.first_ts is None:
            return None
        return 1e3 * max(stream.first_ts - submit_ts, 0.0)


def _sse(event: str, data: dict) -> bytes:
    return (f"event: {event}\ndata: {json.dumps(data, sort_keys=True)}"
            f"\n\n").encode("utf-8")


class _Handler(BaseHTTPRequestHandler):
    server_version = "ds-gateway/1.0"

    # ------------------------------------------------------------------
    def log_message(self, fmt, *args):  # silenced: telemetry covers it
        pass

    @property
    def gw(self) -> ServingGateway:
        return self.server.gateway

    def _json(self, status: int, payload: dict, headers=()):
        body = json.dumps(payload, sort_keys=True).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _error(self, status: int, reason: str, tenant: str = "unknown",
               retry_after: float = 0.0):
        headers = []
        if status in (429, 503):
            secs = max(retry_after, self.gw.config.retry_after_secs)
            headers.append(("Retry-After", str(max(1, round(secs)))))
        self._json(status, {"error": {"status": status, "reason": reason,
                                      "tenant": tenant}}, headers)

    # ------------------------------------------------------------------
    def do_GET(self):
        gw = self.gw
        if self.path in ("/metrics", "/"):
            gw._metrics.counter("ds_scrapes_total").inc()
            body = gw._metrics.expose().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path == "/healthz":
            backend = gw.backend
            payload = {"status": "ok", "pending": bool(gw.pending)}
            overload = getattr(backend, "overload", None)
            if overload is not None:
                payload["overload"] = round(float(overload()), 6)
            gauges = (getattr(backend, "fleet_gauges", None)
                      or getattr(backend, "gauges", None))
            if gauges is not None:
                payload["gauges"] = gauges()
            self._json(200, payload)
            return
        self._json(404, {"error": {"status": 404, "reason": "not_found"}})

    # ------------------------------------------------------------------
    def do_POST(self):
        if self.path != GENERATE_ROUTE:
            self._json(404, {"error": {"status": 404,
                                       "reason": "not_found"}})
            return
        gw = self.gw
        api_key = self._api_key()
        tenant, err = gw.authenticate(api_key)
        label = tenant.name if tenant is not None else "unknown"
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if length <= 0:
            gw._reject(label, "bad_request", 400)
            self._error(400, "bad_request", label)
            return
        if length > gw.config.max_body_bytes:
            gw._reject(label, "too_large", 413)
            self._error(413, "too_large", label)
            return
        if tenant is None:
            status = _REASON_STATUS[err]
            gw._reject(label, err, status)
            self._error(status, err, label)
            return
        raw = self.rfile.read(length)
        body = self._parse(raw)
        if body is None:
            gw._reject(tenant.name, "bad_request", 400)
            self._error(400, "bad_request", tenant.name)
            return
        samp_err = _validate_sampling(body)
        if samp_err is not None:
            gw._reject(tenant.name, samp_err, 400)
            self._error(400, samp_err, tenant.name)
            return
        handle, stream, trace, retry_after, reason = gw.admit(tenant, body)
        if handle is None:
            self._error(_REASON_STATUS.get(reason, 429), reason,
                        tenant.name, retry_after)
            return
        submit_ts = gw.clock()
        if body.get("stream", True):
            self._stream_sse(gw, tenant, handle, stream, trace, submit_ts)
        else:
            self._respond_json(gw, tenant, handle, stream, trace,
                               submit_ts)

    # ------------------------------------------------------------------
    def _api_key(self) -> Optional[str]:
        auth = self.headers.get("Authorization") or ""
        if auth.startswith("Bearer "):
            return auth[len("Bearer "):].strip()
        return self.headers.get("X-API-Key")

    def _parse(self, raw: bytes) -> Optional[dict]:
        try:
            body = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return None
        if not isinstance(body, dict):
            return None
        prompt = body.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            return None
        mnt = body.get("max_new_tokens", 0)
        if not isinstance(mnt, int) or mnt < 0:
            return None
        return body

    # ------------------------------------------------------------------
    def _pull(self, gw: ServingGateway, handle, stream: _Stream):
        """Yield queue items; on quiet polls, fall back to the handle's
        terminal state (a shed mid-decode emits no done marker)."""
        while True:
            try:
                yield stream.q.get(timeout=gw.config.poll_secs)
                continue
            except queue.Empty:
                pass
            if stream.overflow:
                yield ("error", "slow_reader")
                return
            state = getattr(handle, "state", "")
            if state == "shed" and stream.q.empty():
                yield ("error", getattr(handle, "finish_reason", "")
                       or "shed")
                return
            if state == "finished" and stream.q.empty():
                yield ("done",)
                return
            if not gw._running:
                yield ("error", "shutdown")
                return

    def _record_of(self, handle) -> dict:
        rec = getattr(handle, "record", None)
        if not callable(rec):
            return {}
        # the ("done",) marker is enqueued MID-step by the stream
        # callback; the backend marks the request terminal at the END of
        # that same step (router harvest). Wait it out — bounded — so
        # the record this response carries is the final one, not a
        # mid-harvest snapshot with state still "running".
        pause = threading.Event()
        for _ in range(2000):
            if getattr(handle, "state", "finished") in ("finished",
                                                        "shed"):
                break
            pause.wait(0.005)
        return rec()

    def _observe_ttft(self, gw, tenant, stream, submit_ts):
        ttft_ms = gw.observe_ttft(tenant, stream, submit_ts)
        if ttft_ms is not None:
            gw._metrics.histogram("ds_gateway_ttft_ms",
                                  labels=("tenant",)) \
                .labels(tenant=tenant.name).observe(ttft_ms)
        return ttft_ms

    def _stream_sse(self, gw, tenant, handle, stream, trace, submit_ts):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream; charset=utf-8")
        self.send_header("Cache-Control", "no-store")
        self.send_header("X-Request-Id", stream.request_id)
        self.send_header("Connection", "close")
        self.end_headers()
        index = 0
        try:
            for item in self._pull(gw, handle, stream):
                if item[0] == "token":
                    self.wfile.write(_sse("token", {
                        "token": item[1], "index": index,
                        "request_id": stream.request_id}))
                    self.wfile.flush()
                    index += 1
                elif item[0] == "done":
                    ttft = self._observe_ttft(gw, tenant, stream,
                                              submit_ts)
                    record = self._record_of(handle)
                    if record.get("ttft_ms") is None and ttft is not None:
                        # backends that don't stamp timestamps (or use a
                        # different timebase) still report the gateway-
                        # observed TTFT, read on the step thread
                        record["ttft_ms"] = round(ttft, 3)
                    self.wfile.write(_sse("done", record))
                    self.wfile.flush()
                    gw._finish(tenant, stream, "ok", ttft_ms=ttft,
                               trace=trace)
                    return
                else:  # ("error", reason)
                    reason = item[1]
                    self.wfile.write(_sse("error", {
                        "reason": reason,
                        "request_id": stream.request_id}))
                    self.wfile.flush()
                    ttft = self._observe_ttft(gw, tenant, stream,
                                              submit_ts)
                    gw._finish(tenant, stream, "shed", reason=reason,
                               ttft_ms=ttft, trace=trace)
                    return
        except (BrokenPipeError, ConnectionError, OSError):
            # client went away mid-stream: cancel through the backend
            # seam so the slot and its KV blocks are released
            gw._request_cancel(stream.request_id, "disconnect")
            ttft = gw.observe_ttft(tenant, stream, submit_ts)
            gw._finish(tenant, stream, "shed", reason="disconnect",
                       ttft_ms=ttft, trace=trace)

    def _respond_json(self, gw, tenant, handle, stream, trace, submit_ts):
        tokens: List[int] = []
        outcome, reason = "ok", ""
        for item in self._pull(gw, handle, stream):
            if item[0] == "token":
                tokens.append(item[1])
            elif item[0] == "done":
                break
            else:
                outcome, reason = "shed", item[1]
                break
        ttft = self._observe_ttft(gw, tenant, stream, submit_ts)
        record = self._record_of(handle)
        if record.get("ttft_ms") is None and ttft is not None:
            record["ttft_ms"] = round(ttft, 3)
        payload = {"request_id": stream.request_id,
                   "state": "finished" if outcome == "ok" else "shed",
                   "reason": reason, "tokens": tokens, "record": record}
        gw._finish(tenant, stream, outcome, reason=reason, ttft_ms=ttft,
                   trace=trace)
        self._json(200, payload)
