"""Trace-driven workload replay: open arrival-trace format, synthetic
generators, and a faster-than-real-time replayer over fake clocks.

Pure host-side (no jax; no wall-clock reads — replay time is a
:class:`ReplayClock` the target shares, which is what makes a
20-minute diurnal trace replay in milliseconds and makes every run
bit-deterministic given its seed).

**Trace format** — one JSON object per line (JSONL), open by design so
real request logs convert trivially::

    {"arrival_ts": 12.75, "prompt_len": 96, "max_new_tokens": 64,
     "tenant": "t3", "prefix_len": 32, "priority": 1, "deadline_ms": 0}

``arrival_ts`` is seconds from trace start; ``tenant`` groups arrivals
that share a prompt prefix of ``prefix_len`` tokens (the prefix-cache /
shared-system-prompt workload shape); ``priority`` feeds the router's
degradation ladder; ``deadline_ms`` the admission deadline. Optional
``do_sample``/``temperature``/``top_p``/``seed`` fields request KEYED
sampling — absent keys mean greedy, so every pre-sampling trace loads
unchanged, and the per-arrival seed keeps replays bit-deterministic.

**Generators** — :func:`synthesize_trace` samples a nonhomogeneous
Poisson arrival process by thinning (diurnal sinusoid + burst windows
over a base rate), heavy-tailed (lognormal) prompt/generation lengths,
and a Zipf-skewed tenant mix. :func:`diurnal_trace` /
:func:`burst_trace` are named shapes of the same knobs.

**Replayer** — :class:`TraceReplayer` drives anything with the serving
front-door surface (``submit()``/``step()``/``pending``): one
``step()`` per ``step_secs`` of simulated time, submitting every
arrival whose timestamp has passed, synthesizing prompt tokens
deterministically (same seed + same trace = bit-identical prompts —
tenant prefixes shared, tails unique). :meth:`TraceReplayer.report`
reduces the collected request handles to SLO attainment: TTFT
p50/p95, shed rate, tokens/s of simulated time, and the fraction of
arrivals served within a target — aggregate AND per tenant (the
``tenants`` block feeds per-tenant error budgets).

**HTTP driver** — :class:`HttpReplayDriver` is a replay target that
submits THROUGH a running serving gateway over real HTTP: each arrival
becomes a ``POST /v1/generate`` with the tenant's API key, the SSE
stream is consumed by a reader thread, and ``step()`` drives the
gateway's backend on the shared fake clock. Admission is serialized
(``submit`` returns once the gateway answered status + headers), so
quota decisions and token streams stay bit-deterministic.
"""

import dataclasses
import json
import math
import threading
import urllib.error
import urllib.request
import zlib
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.serving.config import ReplayConfig


@dataclasses.dataclass
class Arrival:
    """One trace record. ``request_id`` is optional — the replayer
    numbers arrivals when absent."""

    arrival_ts: float
    prompt_len: int
    max_new_tokens: int
    tenant: str = ""          # shared-prefix group ("" = unshared)
    prefix_len: int = 0       # leading tokens shared across the tenant
    priority: int = 0
    deadline_ms: float = 0.0
    request_id: str = ""
    # ---- keyed sampling (optional; absent keys = greedy, so every
    # pre-sampling trace loads unchanged). ``seed`` makes the sampled
    # stream bit-reproducible — replaying the same trace twice emits
    # identical tokens, which is what lets the SLO report compare runs.
    do_sample: bool = False
    temperature: float = 0.0  # 0 = serving default
    top_p: float = 0.0        # 0 = disabled / serving default
    seed: int = 0

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        # False == 0, so disabled sampling fields drop with the other
        # defaults and round-trip losslessly
        return {k: v for k, v in d.items() if v not in ("", 0, 0.0)
                or k in ("arrival_ts", "prompt_len", "max_new_tokens")}

    @classmethod
    def from_json(cls, d: dict) -> "Arrival":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def save_trace(path: str, arrivals: Sequence[Arrival]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for a in arrivals:
            f.write(json.dumps(a.to_json(), separators=(",", ":")) + "\n")


def load_trace(path: str) -> List["Arrival"]:
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(Arrival.from_json(json.loads(line)))
    out.sort(key=lambda a: a.arrival_ts)
    return out


# ---------------------------------------------------------------------------
# synthetic generators


def _heavy_tail(rng, mean: float, sigma: float, lo: int, hi: int) -> int:
    """Lognormal sample with the requested mean (median below it — the
    heavy tail is real: most draws small, a few near ``hi``)."""
    mu = math.log(max(1.0, float(mean))) - sigma * sigma / 2.0
    return int(np.clip(round(rng.lognormal(mu, sigma)), lo, hi))


def synthesize_trace(duration_secs: float, *, seed: int,
                     base_rate: float = 1.0,
                     diurnal_fraction: float = 0.0,
                     diurnal_period_secs: float = 60.0,
                     bursts: Sequence = (),
                     prompt_len_mean: float = 64.0,
                     prompt_len_sigma: float = 0.6,
                     prompt_len_max: int = 512,
                     gen_mean: float = 32.0,
                     gen_sigma: float = 0.6,
                     gen_max: int = 256,
                     tenants: int = 0,
                     shared_fraction: float = 0.0,
                     shared_prefix_len: int = 0,
                     priorities: int = 1,
                     deadline_ms: float = 0.0,
                     sampled_fraction: float = 0.0,
                     temperature: float = 0.0,
                     top_p: float = 0.0) -> List[Arrival]:
    """Sample one arrival trace, fully deterministic given ``seed``.

    The instantaneous arrival rate is ``base_rate * (1 +
    diurnal_fraction * sin(2*pi*t/period))`` plus every ``(start_secs,
    duration_secs, extra_rate)`` burst window covering ``t`` — sampled
    exactly by Poisson thinning. Prompt and generation lengths are
    lognormal (heavy-tailed). With ``tenants > 0``, ``shared_fraction``
    of arrivals join a Zipf-skewed tenant whose prompts share their
    first ``shared_prefix_len`` tokens (the prefix-cache shape);
    priorities are uniform over ``range(priorities)``. With
    ``sampled_fraction > 0`` that fraction of arrivals carry keyed
    sampling fields (a per-arrival seed plus the given
    ``temperature``/``top_p``); at 0 no extra rng draws happen, so
    legacy traces stay bit-identical for the same seed."""
    if base_rate <= 0 or duration_secs <= 0:
        raise ValueError("synthesize_trace needs base_rate > 0 and "
                         f"duration_secs > 0, got {base_rate}/"
                         f"{duration_secs}")
    if not (0.0 <= diurnal_fraction <= 1.0):
        raise ValueError("diurnal_fraction must be in [0, 1], got "
                         f"{diurnal_fraction}")
    rng = np.random.default_rng(int(seed))
    bursts = [(float(s), float(d), float(r)) for s, d, r in bursts]

    def rate(t: float) -> float:
        r = base_rate * (1.0 + diurnal_fraction
                         * math.sin(2.0 * math.pi * t
                                    / diurnal_period_secs))
        for start, dur, extra in bursts:
            if start <= t < start + dur:
                r += extra
        return max(r, 0.0)

    rate_max = base_rate * (1.0 + diurnal_fraction) \
        + sum(r for _, _, r in bursts)
    out: List[Arrival] = []
    t = 0.0
    while True:
        # thinning: candidate arrivals at rate_max, accepted at rate(t)
        t += float(rng.exponential(1.0 / rate_max))
        if t >= duration_secs:
            break
        if rng.random() >= rate(t) / rate_max:
            continue
        tenant, prefix = "", 0
        if tenants > 0 and shared_fraction > 0 \
                and rng.random() < shared_fraction:
            # Zipf-skewed popularity: tenant 1 is the hot system prompt
            # (the distribution's unbounded tail folds into it, so the
            # hottest tenant really is t1, not the clip boundary)
            z = int(rng.zipf(1.5))
            tenant = f"t{z if z <= tenants else 1}"
            prefix = int(shared_prefix_len)
        p_lo = max(1, prefix + 1)   # at least one unshared prompt token
        samp = {}
        if sampled_fraction > 0 and rng.random() < sampled_fraction:
            samp = {"do_sample": True,
                    "seed": int(rng.integers(1, 2**31 - 1)),
                    "temperature": float(temperature),
                    "top_p": float(top_p)}
        out.append(Arrival(
            arrival_ts=round(t, 6),
            prompt_len=max(p_lo, _heavy_tail(rng, prompt_len_mean,
                                             prompt_len_sigma, 1,
                                             prompt_len_max)),
            max_new_tokens=_heavy_tail(rng, gen_mean, gen_sigma, 1,
                                       gen_max),
            tenant=tenant, prefix_len=prefix,
            priority=int(rng.integers(0, max(1, priorities))),
            deadline_ms=float(deadline_ms), **samp))
    return out


def diurnal_trace(duration_secs: float, *, seed: int, base_rate: float,
                  peak_fraction: float = 0.5,
                  period_secs: float = 60.0, **kw) -> List[Arrival]:
    """A diurnal wave: rate swings ``±peak_fraction`` around base."""
    return synthesize_trace(duration_secs, seed=seed, base_rate=base_rate,
                            diurnal_fraction=peak_fraction,
                            diurnal_period_secs=period_secs, **kw)


def burst_trace(duration_secs: float, *, seed: int, base_rate: float,
                bursts: Sequence, **kw) -> List[Arrival]:
    """Poisson bursts over a flat base rate: ``bursts`` is a sequence of
    ``(start_secs, duration_secs, extra_rate)`` windows."""
    return synthesize_trace(duration_secs, seed=seed, base_rate=base_rate,
                            bursts=bursts, **kw)


# ---------------------------------------------------------------------------
# replay


class ReplayClock:
    """The injectable fake clock the replayer advances and the target
    (router/scheduler/health/autoscaler) reads — simulated seconds,
    decoupled from wall time. Also quacks as an injectable ``sleep`` so
    chaos stalls advance simulated time instead of blocking the test."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, secs: float) -> None:
        self.t += float(secs)

    sleep = advance


def _pct(values, q: float):
    if not values:
        return None
    vs = sorted(values)
    k = min(len(vs) - 1, max(0, math.ceil(q / 100.0 * len(vs)) - 1))
    return round(float(vs[k]), 3)


class TraceReplayer:
    """Replay one arrival trace against a serving front door.

    ``target`` is anything with ``submit()``/``step()``/``pending`` —
    a :class:`~deepspeed_tpu.serving.router.ReplicaRouter`, a
    :class:`~deepspeed_tpu.serving.router.FleetManager`, or a single
    ``ServingEngine``. ``clock`` must be the same :class:`ReplayClock`
    the target was built with (replay determinism is exactly this: one
    simulated timebase everywhere). ``on_step(replayer, done_records)``
    fires after every step — the seam the capacity model and tests hook.
    """

    def __init__(self, target, trace: Sequence[Arrival], clock: ReplayClock,
                 *, config: Optional[ReplayConfig] = None,
                 step_secs: Optional[float] = None, seed: Optional[int] = None,
                 vocab_size: Optional[int] = None,
                 max_steps: Optional[int] = None,
                 on_step: Optional[Callable] = None):
        if config is None:
            config = ReplayConfig()
        elif isinstance(config, dict):
            config = ReplayConfig(**config)
        self.target = target
        self.trace = sorted(trace, key=lambda a: a.arrival_ts)
        self.clock = clock
        self.step_secs = float(step_secs if step_secs is not None
                               else config.step_secs)
        self.seed = int(seed if seed is not None else config.seed)
        self.vocab = int(vocab_size if vocab_size is not None
                         else config.vocab_size)
        self.max_steps = int(max_steps if max_steps is not None
                             else config.max_steps)
        self.on_step = on_step
        # the router front door takes priority; a bare ServingEngine
        # does not — probe the surface once, not per submit
        self._routerlike = hasattr(target, "overload") \
            or hasattr(target, "router")
        self.handles: List = []
        self.tenants: List[str] = []   # aligned with handles
        self.steps = 0
        self._t0 = clock()

    # ------------------------------------------------------------------
    def prompt_for(self, arrival: Arrival, index: int) -> List[int]:
        """Deterministic token synthesis: a tenant's shared prefix comes
        from the tenant's own stream (identical across its arrivals —
        what the prefix cache deduplicates), the tail from the arrival's
        stream (unique)."""
        n, prefix = int(arrival.prompt_len), 0
        tokens: List[int] = []
        if arrival.tenant and arrival.prefix_len > 0:
            prefix = min(int(arrival.prefix_len), n - 1)
            # crc32, not hash(): str hashing is salted per process and
            # would break cross-process replay determinism
            trng = np.random.default_rng(
                [self.seed, zlib.crc32(arrival.tenant.encode())])
            tokens += [int(x) for x in
                       trng.integers(1, self.vocab, prefix)]
        arng = np.random.default_rng([self.seed, 0x5EED, index])
        tokens += [int(x) for x in
                   arng.integers(1, self.vocab, n - prefix)]
        return tokens

    def _submit(self, arrival: Arrival, index: int):
        kwargs = dict(max_new_tokens=int(arrival.max_new_tokens),
                      request_id=arrival.request_id or f"replay-{index}",
                      deadline_ms=float(arrival.deadline_ms))
        if arrival.do_sample:
            kwargs["do_sample"] = True
            kwargs["seed"] = int(arrival.seed)
            if arrival.temperature:
                kwargs["temperature"] = float(arrival.temperature)
            if arrival.top_p:
                kwargs["top_p"] = float(arrival.top_p)
        if self._routerlike:
            kwargs["priority"] = int(arrival.priority)
        if getattr(self.target, "accepts_tenant", False):
            # the HTTP driver maps the tenant to its API key
            kwargs["tenant"] = arrival.tenant
        return self.target.submit(self.prompt_for(arrival, index), **kwargs)

    def run(self) -> dict:
        """Replay to completion (trace exhausted AND target drained, or
        ``max_steps``); returns :meth:`report`'s payload."""
        i = 0
        while i < len(self.trace) or self.target.pending:
            now = self.clock()
            while i < len(self.trace) and self.trace[i].arrival_ts <= now:
                self.handles.append(self._submit(self.trace[i], i))
                self.tenants.append(self.trace[i].tenant or "")
                i += 1
            done = self.target.step()
            self.steps += 1
            if self.on_step is not None:
                self.on_step(self, done)
            self.clock.advance(self.step_secs)
            if self.max_steps and self.steps >= self.max_steps:
                break
        # HTTP-driver seam: wait (real time, no simulated steps) for the
        # reader threads to drain their streams before reporting
        finish = getattr(self.target, "finish", None)
        if finish is not None:
            finish()
        return self.report()

    # ------------------------------------------------------------------
    def _reduce(self, recs: List[dict], slo: Optional[dict]) -> dict:
        """One TTFT/shed/attainment block over a record subset (the
        aggregate report and every per-tenant row share this shape)."""
        finished = [r for r in recs if r["state"] == "finished"]
        shed = [r for r in recs if r["state"] == "shed"]
        ttfts = [r["ttft_ms"] for r in finished
                 if r.get("ttft_ms") is not None]
        out = {
            "requests": len(recs),
            "finished": len(finished),
            "shed": len(shed),
            "shed_rate": round(len(shed) / len(recs), 4) if recs else None,
            "tokens_out": sum(r.get("new_tokens") or 0 for r in finished),
            "ttft_ms_p50": _pct(ttfts, 50),
            "ttft_ms_p95": _pct(ttfts, 95),
        }
        if slo:
            target = float(slo.get("ttft_p95_ms") or 0.0)
            good = [r for r in finished
                    if not target or (r.get("ttft_ms") is not None
                                      and r["ttft_ms"] <= target)]
            out["slo_attainment"] = (round(len(good) / len(recs), 4)
                                     if recs else None)
        return out

    def report(self, slo: Optional[dict] = None) -> dict:
        """SLO attainment over every replayed arrival. With ``slo``
        (``{"ttft_p95_ms": X}``) adds ``slo_attainment`` — the fraction
        of arrivals that finished with TTFT within the target (a shed
        arrival is a miss by definition) — and ``slo_ok``, whether the
        aggregate window met both targets. Traces with tenant labels get
        a ``tenants`` block: the same TTFT/shed/attainment breakdown per
        tenant (one aggregate line would hide a starved tenant behind a
        healthy mix — this is what per-tenant error budgets read)."""
        recs = [h.record() for h in self.handles]
        finished = [r for r in recs if r["state"] == "finished"]
        shed = [r for r in recs if r["state"] == "shed"]
        ttfts = [r["ttft_ms"] for r in finished
                 if r.get("ttft_ms") is not None]
        sim_secs = self.clock() - self._t0
        tokens = sum(r.get("new_tokens") or 0 for r in finished)
        out = {
            "requests": len(recs),
            "finished": len(finished),
            "shed": len(shed),
            "shed_rate": round(len(shed) / len(recs), 4) if recs else None,
            "incomplete": len(recs) - len(finished) - len(shed),
            "tokens_out": tokens,
            "sim_secs": round(sim_secs, 6),
            "steps": self.steps,
            "tokens_per_sim_sec": round(tokens / sim_secs, 2)
            if sim_secs > 0 else None,
            "ttft_ms_p50": _pct(ttfts, 50),
            "ttft_ms_p95": _pct(ttfts, 95),
        }
        if slo:
            target = float(slo.get("ttft_p95_ms") or 0.0)
            good = [r for r in finished
                    if not target or (r.get("ttft_ms") is not None
                                      and r["ttft_ms"] <= target)]
            out["slo_attainment"] = (round(len(good) / len(recs), 4)
                                     if recs else None)
            shed_target = slo.get("shed_rate")
            out["slo_ok"] = bool(
                (not target or (out["ttft_ms_p95"] is not None
                                and out["ttft_ms_p95"] <= target))
                and (shed_target is None
                     or (out["shed_rate"] or 0.0) <= float(shed_target)))
        if (len(self.tenants) == len(recs)
                and any(t for t in self.tenants)):
            by_tenant: Dict[str, List[dict]] = {}
            for tenant, rec in zip(self.tenants, recs):
                by_tenant.setdefault(tenant or "", []).append(rec)
            out["tenants"] = {tenant: self._reduce(by_tenant[tenant], slo)
                              for tenant in sorted(by_tenant)}
        if any(r.get("do_sample") for r in recs):
            # keyed sampling adds in-graph filtering work to every
            # decode step of a sampled slot: the SLO split keeps the two
            # populations' TTFT/shed/attainment from masking each other
            out["sampling"] = {
                "sampled": self._reduce(
                    [r for r in recs if r.get("do_sample")], slo),
                "greedy": self._reduce(
                    [r for r in recs if not r.get("do_sample")], slo),
            }
        return out


# ---------------------------------------------------------------------------
# HTTP driver: replay THROUGH the serving gateway

class _HttpHandle:
    """A replay handle for one HTTP request: quacks like a Request
    (``state`` / ``done`` / ``record()``) so the replayer's report path
    is identical either way. Terminal state comes from the server — the
    ``done`` SSE event carries the backend's own record."""

    def __init__(self, request_id: str, prompt_len: int,
                 do_sample: bool = False):
        self.request_id = request_id
        self.state = "queued"
        self.tokens: List[int] = []
        self.finished = threading.Event()
        # do_sample is stamped at submit so a REJECTED sampled request
        # still lands in the report's sampled population
        self._record = {"request_id": request_id, "state": self.state,
                        "reason": None, "prompt_len": prompt_len,
                        "do_sample": bool(do_sample),
                        "new_tokens": 0, "ttft_ms": None}

    @property
    def done(self) -> bool:
        return self.state in ("finished", "shed")

    def reject(self, status: int, reason: str):
        self.state = "shed"
        self._record.update(state="shed", reason=reason,
                            http_status=status)
        self.finished.set()

    def finish(self, record: dict):
        self.state = str(record.get("state") or "finished")
        self._record.update(record)
        self._record["state"] = self.state
        self.finished.set()

    def error(self, reason: str):
        self.state = "shed"
        self._record.update(state="shed", reason=reason,
                            new_tokens=len(self.tokens))
        self.finished.set()

    def record(self) -> dict:
        rec = dict(self._record)
        rec.setdefault("new_tokens", len(self.tokens))
        return rec


class HttpReplayDriver:
    """Replay target that routes every submit through a running
    :class:`~deepspeed_tpu.serving.gateway.ServingGateway` over real
    HTTP. ``submit()`` POSTs to ``/v1/generate`` with the tenant's API
    key and returns once the gateway answered (status + headers) — so
    admission/quota decisions interleave deterministically with the
    fake-clock step loop — then a daemon reader thread consumes the SSE
    stream into the handle. ``step()`` drives the gateway (deferred
    cancels + one backend step)."""

    accepts_tenant = True

    def __init__(self, gateway, *, api_keys: Optional[Dict[str, str]] = None,
                 timeout_secs: float = 60.0):
        self.gateway = gateway
        self.url = gateway.url
        if api_keys is None:
            api_keys = {t.name: t.api_key
                        for t in gateway.config.tenants}
        self.api_keys = api_keys
        self.timeout_secs = float(timeout_secs)
        self._threads: List[threading.Thread] = []
        self._count = 0

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 0, request_id: str = "",
               deadline_ms: float = 0.0, tenant: str = "",
               do_sample: bool = False, seed: Optional[int] = None,
               temperature: Optional[float] = None,
               top_p: Optional[float] = None, **kwargs) -> _HttpHandle:
        self._count += 1
        request_id = request_id or f"http-{self._count}"
        handle = _HttpHandle(request_id, len(prompt), do_sample=do_sample)
        body = {"prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens),
                "request_id": request_id, "stream": True}
        if deadline_ms:
            body["deadline_ms"] = float(deadline_ms)
        if do_sample:
            body["do_sample"] = True
            if seed is not None:
                body["seed"] = int(seed)
            if temperature is not None:
                body["temperature"] = float(temperature)
            if top_p is not None:
                body["top_p"] = float(top_p)
        headers = {"Content-Type": "application/json"}
        key = self.api_keys.get(tenant)
        if key:
            headers["Authorization"] = f"Bearer {key}"
        req = urllib.request.Request(self.url + "/v1/generate",
                                     data=json.dumps(body).encode("utf-8"),
                                     headers=headers, method="POST")
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_secs)
        except urllib.error.HTTPError as exc:
            try:
                payload = json.loads(exc.read().decode("utf-8"))
                reason = payload.get("error", {}).get("reason", "")
            except Exception:
                reason = ""
            exc.close()
            handle.reject(exc.code, f"gateway_{reason or exc.code}")
            return handle
        reader = threading.Thread(target=self._read_sse,
                                  args=(resp, handle), daemon=True)
        reader.start()
        self._threads.append(reader)
        return handle

    @staticmethod
    def _read_sse(resp, handle: _HttpHandle):
        event, data = "", ""
        try:
            for raw in resp:
                line = raw.decode("utf-8").rstrip("\n")
                if line.startswith("event: "):
                    event = line[len("event: "):]
                elif line.startswith("data: "):
                    data = line[len("data: "):]
                elif line == "":
                    if event == "token":
                        handle.tokens.append(int(json.loads(data)["token"]))
                    elif event == "done":
                        handle.finish(json.loads(data))
                        return
                    elif event == "error":
                        handle.error(str(json.loads(data).get("reason")
                                         or "stream_error"))
                        return
                    event, data = "", ""
        except (OSError, ValueError):
            pass
        finally:
            resp.close()
            if not handle.finished.is_set():
                handle.error("stream_closed")

    # ------------------------------------------------------------------
    def step(self):
        return self.gateway.step()

    @property
    def pending(self) -> bool:
        return self.gateway.pending

    def drain(self, max_steps: Optional[int] = None):
        return self.gateway.drain(max_steps)

    def finish(self):
        """Join every reader thread (bounded): streams whose backend
        work completed finish without further steps; a stuck stream
        times out and stays incomplete in the report."""
        for thread in self._threads:
            thread.join(self.timeout_secs)
        self._threads = [t for t in self._threads if t.is_alive()]
