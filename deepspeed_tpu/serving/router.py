"""Resilient multi-replica front door: health-aware routing, failover
with deterministic replay, and an SLO-guarded degradation ladder.

Pure host-side policy (no jax imports — the tier-1 tests drive it with
fake replicas in milliseconds). A *replica* is anything with the
``ServingEngine`` surface: ``submit(prompt, max_new_tokens, request_id,
eos_token_id, deadline_ms, stream)`` returning a live ``Request``,
``step()``, ``gauges()`` and ``stats()``. The router composes N of them
behind one ``submit()``/``step()``/``drain()`` surface:

- **routing** — each submit goes to the least-loaded routable replica
  (load = ``queue_depth + slots_busy`` from the public ``gauges()``
  payload, the same numbers the per-step serving telemetry events
  carry): HEALTHY replicas first, DEGRADED only when no HEALTHY peer
  can take it, plus at most one half-open probe to a TRIPPED replica
  whose backoff elapsed.
- **failover with deterministic replay** — greedy decode is
  bit-reproducible (the PR 4 batch-invariance guarantee), so when a
  replica dies or its breaker trips the router resubmits every one of
  its in-flight requests — full prompt, the ORIGINAL effective
  ``max_new_tokens`` — to a survivor and dedupes the regenerated stream
  by position: tokens the client already saw are swallowed (and checked
  — a mismatch is a loud ``replay.divergence`` event, the greedy
  contract broken), new positions stream exactly once. The client sees
  one uninterrupted exactly-once token stream, not a restart.
- **degradation ladder** — under aggregate overload (queue depth over
  capacity across routable replicas) the router walks explicit tiers
  instead of collapsing into timeout storms: full service -> clamp
  ``max_new_tokens`` -> shed below-priority-floor work -> brownout
  (smallest-bucket prompts only). Tier entry is immediate; exit needs
  the score below the (lower) exit threshold for ``ladder_dwell_steps``
  — the hysteresis guard.

Every transition — replica state, breaker trip/probe/close, failover,
tier — is a ``router`` telemetry event on the unified stream
(rendered by ``tools/telemetry_report.py``).
"""

import dataclasses
import itertools
import math
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set

from deepspeed_tpu.serving import request as rq
from deepspeed_tpu.serving.autoscaler import (SCALE_DOWN, SCALE_UP,
                                              Autoscaler, Decision)
from deepspeed_tpu.serving.config import FleetConfig, RouterConfig
from deepspeed_tpu.serving.health import (DEAD, DEGRADED, DRAINING, HEALTHY,
                                          STATES, TRIPPED, ReplicaHealth)
from deepspeed_tpu.serving.migration import Migrator, resolve_migration
from deepspeed_tpu.telemetry.registry import NULL_REGISTRY
from deepspeed_tpu.telemetry.tracing import (NULL_TRACER, end_span, span_id,
                                             to_ns, trace_ctx)

_ids = itertools.count()


@dataclasses.dataclass
class RouterRequest:
    """The client's handle: mirrors ``Request`` (state / tokens / stream)
    but survives its replica. ``tokens`` holds exactly the tokens the
    client's stream callback saw, in order — across any number of
    failovers, each position exactly once."""

    prompt: List[int]
    max_new_tokens: int = 0       # effective budget, pinned at first dispatch
    request_id: str = ""
    priority: int = 0             # ladder tier 2+ sheds below the floor
    eos_token_id: int = -1
    deadline_ms: float = 0.0
    stream: Optional[Callable] = None
    # ---- keyed sampling: replayable state. A seeded sampled request's
    # tokens are a pure function of (seed, position, logits), so the
    # dedupe-splice exactly-once contract extends to it unchanged —
    # failover replays regenerate the delivered prefix bit-identically.
    do_sample: bool = False
    seed: Optional[int] = None
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None

    # ---- runtime state (owned by the router) ----
    clamp_budget: int = 0         # tier-1 cap pending default resolution
    state: str = rq.QUEUED
    finish_reason: Optional[str] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    replica: int = -1             # current assignment
    attempt: int = 0              # failovers so far
    proxy: Optional[rq.Request] = None
    submit_ts: float = 0.0
    first_token_ts: float = 0.0
    finish_ts: float = 0.0
    # ---- span tracing (telemetry/tracing.py; None with tracing off) ----
    trace_id: Optional[str] = None     # ONE trace across every failover
    root_span: Optional[object] = None     # open `request` root handle
    attempt_span: Optional[object] = None  # open `attempt` subtree handle
    attempt_start_pos: int = 0    # first NEW position this attempt streams
    # first/last delivery time this attempt (None = nothing delivered
    # yet: a fake clock's legitimate t=0.0 must not read as unset)
    deliver_t0: Optional[float] = None
    deliver_t1: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def done(self) -> bool:
        return self.state in (rq.FINISHED, rq.SHED)

    @property
    def keyed(self) -> bool:
        """Seeded sampled request — bit-exactly replayable anywhere."""
        return self.do_sample and self.seed is not None

    def record(self) -> dict:
        return {
            "request_id": self.request_id, "state": self.state,
            "reason": self.finish_reason, "prompt_len": self.prompt_len,
            "do_sample": bool(self.do_sample),
            "new_tokens": len(self.tokens), "failovers": self.attempt,
            "ttft_ms": round(1e3 * (self.first_token_ts - self.submit_ts), 3)
            if self.first_token_ts else None,
        }


def _pct(values, q: float):
    if not values:
        return None
    vs = sorted(values)
    k = min(len(vs) - 1, max(0, math.ceil(q / 100.0 * len(vs)) - 1))
    return round(float(vs[k]), 3)


class _NullTelemetry:
    enabled = False

    def emit(self, *a, **k):
        pass


class ReplicaRouter:
    def __init__(self, replicas, config=None, clock=time.monotonic,
                 telemetry=None, migration=None):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.replicas = list(replicas)
        if config is None:
            config = RouterConfig()
        elif isinstance(config, dict):
            config = RouterConfig(**config)
        self.config: RouterConfig = config
        self.clock = clock
        self.telemetry = (telemetry
                          or getattr(self.replicas[0], "telemetry", None)
                          or _NullTelemetry())
        # span tracer: client-side request traces (root + per-dispatch
        # attempt subtrees + exactly-once deliver spans). A failover
        # continues the SAME trace on the survivor — the replicas join
        # it through the context stamped on their proxy requests.
        self._tracer = (getattr(self.telemetry, "tracer", None)
                        or NULL_TRACER)
        # live KV migration (serving.migration): move a failed/draining
        # replica's in-flight state to a survivor instead of replaying
        # it. None (the default) = migration does not exist — failover
        # replays, drains wait, behavior byte-for-byte pre-migration.
        self.migration = resolve_migration(migration)
        self._migrator = (Migrator(
            self.migration, tracer=self._tracer,
            metrics=getattr(self.telemetry, "metrics", None), clock=clock)
            if self.migration is not None and self.migration.enabled
            else None)
        self.health = [ReplicaHealth(config, i, clock, emit=self._emit)
                       for i in range(len(self.replicas))]
        self.tier = 0
        self._tier_changed_step = 0
        self._step_count = 0
        self.requests: Dict[str, RouterRequest] = {}   # live client requests
        self._assigned: List[Set[str]] = [set() for _ in self.replicas]
        self._probe_req: Dict[int, str] = {}           # replica -> request id
        self._done_this_step: List[RouterRequest] = []
        self.finished = deque(maxlen=1024)
        self._counters = {"submitted": 0, "finished": 0, "shed": 0,
                          "failovers": 0, "migrations": 0,
                          "deduped_tokens": 0,
                          "replay_divergence": 0, "tier_transitions": 0,
                          "shed_reasons": {}}

    # ------------------------------------------------------------------
    def _emit(self, name: str, **data):
        self.telemetry.emit("router", name, step=self._step_count, **data)

    def _gauges(self, idx: int) -> dict:
        try:
            return self.replicas[idx].gauges()
        except Exception:
            return {}

    def _load(self, idx: int) -> int:
        g = self._gauges(idx)
        return int(g.get("queue_depth", 0)) + int(g.get("slots_busy", 0))

    def _sampling(self, idx: int) -> bool:
        return bool(getattr(getattr(self.replicas[idx], "config", None),
                            "do_sample", False))

    def _smallest_bucket(self) -> Optional[int]:
        sizes = [min(b) for r in self.replicas
                 for b in [getattr(r, "buckets", None)] if b]
        return min(sizes) if sizes else None

    # ------------------------------------------------------------------
    # routing
    def _candidates(self, now: float, exclude=()) -> List[int]:
        """Routable replicas in preference order — HEALTHY by load, then
        DEGRADED by load, then TRIPPED replicas whose half-open probe
        window is open (each takes exactly one request)."""
        healthy, degraded, probes = [], [], []
        for i, h in enumerate(self.health):
            if i in exclude:
                continue
            if h.state == HEALTHY:
                healthy.append(i)
            elif h.state == DEGRADED:
                degraded.append(i)
            elif h.can_probe(now) and i not in self._probe_req:
                probes.append(i)
        return (sorted(healthy, key=self._load)
                + sorted(degraded, key=self._load) + sorted(probes))

    def submit(self, prompt, max_new_tokens: int = 0, priority: int = 0,
               request_id: Optional[str] = None, eos_token_id: int = -1,
               deadline_ms: float = 0.0,
               stream: Optional[Callable] = None, do_sample: bool = False,
               seed: Optional[int] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None) -> RouterRequest:
        """Route one request to a replica (non-blocking). The returned
        handle's ``state`` is ``queued`` on success, or ``shed`` with a
        ``finish_reason`` when the degradation ladder or every routable
        replica rejected it."""
        now = self.clock()
        rreq = RouterRequest(
            prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            request_id=request_id or f"rr-{next(_ids)}",
            priority=int(priority), eos_token_id=int(eos_token_id),
            deadline_ms=float(deadline_ms), stream=stream,
            do_sample=bool(do_sample),
            seed=int(seed) if seed is not None else None,
            temperature=float(temperature) if temperature is not None
            else None,
            top_k=int(top_k) if top_k is not None else None,
            top_p=float(top_p) if top_p is not None else None)
        rreq.submit_ts = now
        self._counters["submitted"] += 1
        if self._tracer.enabled:
            rreq.trace_id = self._tracer.new_trace(hint=rreq.request_id)
            rreq.root_span = self._tracer.begin(
                "request", rreq.trace_id, start_ns=to_ns(now),
                request_id=rreq.request_id, prompt_len=rreq.prompt_len)
        if rreq.request_id in self.requests:
            return self._shed(rreq, "duplicate_id")
        # ---- degradation ladder admission ----
        c = self.config
        if self.tier >= 1:
            if rreq.max_new_tokens > 0:
                rreq.max_new_tokens = min(rreq.max_new_tokens,
                                          c.clamp_max_new_tokens)
            else:
                # budget comes from the replica default — cap it at
                # dispatch, once known: the clamp must never RAISE the
                # decode work of a default-budget submit
                rreq.clamp_budget = c.clamp_max_new_tokens
        if self.tier >= 2 and rreq.priority < c.shed_priority_floor:
            return self._shed(rreq, "tier_shed")
        if self.tier >= 3:
            floor = self._smallest_bucket()
            if floor is not None and rreq.prompt_len > floor:
                return self._shed(rreq, "brownout")
        if self._dispatch(rreq, now):
            self.requests[rreq.request_id] = rreq
        return rreq

    def _dispatch(self, rreq: RouterRequest, now: float,
                  exclude=()) -> bool:
        """Try candidates in preference order until one accepts; shed the
        request (last replica-side reason, or ``no_replica``) when none
        does. The effective ``max_new_tokens`` was pinned at first
        dispatch, so a failover replays the exact same decode."""
        last_reason = None
        deadline_ms = rreq.deadline_ms
        if deadline_ms:
            # the client's deadline does not restart on failover: the
            # survivor's scheduler stamps a fresh submit_ts, so hand it
            # only the REMAINING budget — and shed already-over-deadline
            # work instead of replaying it arbitrarily late
            deadline_ms -= 1e3 * (now - rreq.submit_ts)
            if deadline_ms <= 0:
                self._shed(rreq, "deadline")
                return False
        for idx in self._candidates(now, exclude):
            h = self.health[idx]
            probe = h.state == TRIPPED
            if rreq.tokens and self._sampling(idx) and not rreq.keyed:
                # the dedupe-splice is only sound across bit-reproducible
                # decodes: a delivered prefix must never resume on an
                # UNSEEDED-sampling replica (a request with nothing
                # streamed yet is fine — there is nothing to splice). A
                # KEYED request regenerates its prefix bit-identically
                # from (seed, position), so it splices like greedy.
                last_reason = "nondeterministic_replay"
                continue
            budget = rreq.max_new_tokens
            if budget <= 0 and rreq.clamp_budget:
                # resolve the replica's default budget and cap it (real
                # engines expose it on .config; without one, the cap
                # itself is the degraded-mode budget)
                default = getattr(getattr(self.replicas[idx], "config",
                                          None),
                                  "default_max_new_tokens", 0) or 0
                budget = (min(int(default), rreq.clamp_budget)
                          if default > 0 else rreq.clamp_budget)
            # sampling kwargs ride only on sampled requests so legacy
            # replica doubles (narrow submit signatures) keep working
            samp_kw = ({"do_sample": True, "seed": rreq.seed,
                        "temperature": rreq.temperature,
                        "top_k": rreq.top_k, "top_p": rreq.top_p}
                       if rreq.do_sample else {})
            try:
                proxy = self.replicas[idx].submit(
                    rreq.prompt, max_new_tokens=budget,
                    request_id=f"{rreq.request_id}#a{rreq.attempt}",
                    eos_token_id=rreq.eos_token_id,
                    deadline_ms=deadline_ms, stream=self._shim(rreq),
                    **samp_kw)
            except Exception as e:
                if probe:
                    # the half-open probe itself failed: it must count
                    # as one (re-trip, backoff doubles) — not as a
                    # first consecutive failure that leaves the probe
                    # window open for immediate hammering
                    h.begin_probe()
                self._replica_failed(
                    idx, f"submit:{type(e).__name__}",
                    fatal=bool(getattr(e, "replica_dead", False)))
                continue
            if proxy.state == rq.SHED:
                last_reason = proxy.finish_reason  # admission said no; next
                continue
            if probe:
                h.begin_probe()
                self._probe_req[idx] = rreq.request_id
            if rreq.max_new_tokens <= 0:
                # pin the effective budget only from an admission that
                # ACCEPTED — the clamp-resolved cap, or the default this
                # replica's proxy reports; a failed candidate's config
                # must not leak into the replay budget
                rreq.max_new_tokens = int(
                    budget or getattr(proxy, "max_new_tokens", 0) or 0)
            rreq.proxy, rreq.replica, rreq.state = proxy, idx, rq.QUEUED
            self._assigned[idx].add(rreq.request_id)
            if self._tracer.enabled:
                # one `attempt` subtree per dispatch; the proxy carries
                # the context so the replica's serve/queue/prefill/
                # decode spans nest under it — ONE trace end to end,
                # failovers included
                rreq.attempt_span = self._tracer.begin(
                    "attempt", rreq.trace_id, parent=span_id(rreq.root_span),
                    start_ns=to_ns(now), attempt=rreq.attempt, replica=idx)
                rreq.attempt_start_pos = len(rreq.tokens)
                rreq.deliver_t0 = rreq.deliver_t1 = None
                proxy.trace = trace_ctx(rreq.trace_id,
                                        parent=span_id(rreq.attempt_span),
                                        attempt=rreq.attempt)
            return True
        self._shed(rreq, last_reason or "no_replica")
        return False

    def _shim(self, rreq: RouterRequest) -> Callable:
        """Per-token dedupe-by-position: the exactly-once guarantee. A
        replayed position must carry the identical token (greedy decode
        is bit-reproducible) — a mismatch is counted and shouted, never
        silently re-streamed."""

        def cb(proxy: rq.Request, tok: int, done: bool):
            if rreq.proxy is not proxy:
                # stale attempt: the request moved on (failed over, or
                # already reported done) — a zombie proxy left decoding
                # on a recovered replica must never resurrect the handle
                # or re-invoke the client stream
                return
            pos = len(proxy.tokens) - 1
            tok = int(tok)
            if pos < len(rreq.tokens):
                self._counters["deduped_tokens"] += 1
                if rreq.tokens[pos] != tok:
                    self._counters["replay_divergence"] += 1
                    self._emit("replay.divergence",
                               request_id=rreq.request_id, position=pos,
                               streamed=rreq.tokens[pos], replayed=tok)
                return
            now = self.clock()
            if not rreq.tokens:
                rreq.first_token_ts = now
            if rreq.deliver_t0 is None:
                rreq.deliver_t0 = now
            rreq.deliver_t1 = now
            rreq.state = rq.RUNNING
            rreq.tokens.append(tok)
            if rreq.stream is not None:
                rreq.stream(rreq, tok, bool(done))

        return cb

    # ------------------------------------------------------------------
    # stepping + health
    def step(self) -> List[RouterRequest]:
        """One router iteration: step every replica that holds work
        (guarded — an exception or stall verdict becomes a health signal
        and a failover), harvest finished/shed proxies, refresh soft
        health from telemetry aggregates, walk the degradation ladder.
        Returns the client requests finished this step."""
        self._step_count += 1
        self._done_this_step = []
        c = self.config
        for idx in range(len(self.replicas)):
            if not self._assigned[idx] or not self.health[idx].alive:
                continue
            t0 = self.clock()
            try:
                self.replicas[idx].step()
            except Exception as e:
                self._replica_failed(
                    idx, f"step:{type(e).__name__}",
                    fatal=bool(getattr(e, "replica_dead", False)))
                continue
            # harvest BEFORE the stall verdict: a slow-but-complete step
            # delivered tokens — requests it finished must not be
            # replayed (or worse, shed) by the failover below
            self._harvest(idx)
            if (c.stall_timeout_secs
                    and self.clock() - t0 >= c.stall_timeout_secs):
                h = self.health[idx]
                h.record_stall("stall")
                self._probe_req.pop(idx, None)
                # DRAINING holds the drain-in-place contract even on a
                # stall verdict (trip() already no-ops there) — mirror
                # the exception path's guard in _replica_failed
                if not h.routable and h.state != DRAINING:
                    self._failover_replica(idx, "stall")
            else:
                self.health[idx].record_success()
        self._observe_health()
        self._evaluate_ladder()
        # snapshot: a later submit-time shed appends to the live list
        # and must not retroactively grow the caller's result
        return list(self._done_this_step)

    def _harvest(self, idx: int):
        for rid in list(self._assigned[idx]):
            rreq = self.requests.get(rid)
            if rreq is None or rreq.proxy is None:
                self._assigned[idx].discard(rid)
                continue
            st = rreq.proxy.state
            if st == rq.FINISHED:
                self._assigned[idx].discard(rid)
                if self._probe_req.get(idx) == rid:
                    del self._probe_req[idx]
                    self.health[idx].probe_success()
                self._finalize(rreq, rreq.proxy.finish_reason)
            elif st == rq.SHED:
                # replica-side policy shed (deadline/queue) — propagate,
                # no failover: resubmitting over-deadline work would feed
                # the very overload the shed relieved
                self._assigned[idx].discard(rid)
                if self._probe_req.get(idx) == rid:
                    del self._probe_req[idx]
                    self.health[idx].probe_inconclusive()
                self._shed(rreq, rreq.proxy.finish_reason or "replica_shed")
        h = self.health[idx]
        if h.state == DRAINING and not self._assigned[idx]:
            self._emit("replica.drained", replica=idx)

    def _close_attempt(self, rreq: RouterRequest, outcome: str):
        """End the open ``attempt`` subtree: a ``deliver`` child records
        exactly the NEW positions this attempt streamed to the client
        (replayed/deduped positions are an attrs counter, never a second
        deliver span — the exactly-once contract, visible in the trace),
        then the attempt span closes with its outcome."""
        if rreq.attempt_span is None:
            return
        now = self.clock()
        delivered = len(rreq.tokens) - rreq.attempt_start_pos
        if delivered > 0:
            t0 = now if rreq.deliver_t0 is None else rreq.deliver_t0
            t1 = now if rreq.deliver_t1 is None else rreq.deliver_t1
            self._tracer.record_span(
                "deliver", rreq.trace_id, to_ns(t0), to_ns(t1),
                parent=span_id(rreq.attempt_span),
                from_pos=rreq.attempt_start_pos, to_pos=len(rreq.tokens),
                tokens=delivered)
        end_span(rreq.attempt_span, end_ns=to_ns(now), outcome=outcome,
                 delivered=delivered)
        rreq.attempt_span = None

    def _close_root(self, rreq: RouterRequest):
        end_span(rreq.root_span, end_ns=to_ns(rreq.finish_ts),
                 state=rreq.state, reason=rreq.finish_reason,
                 failovers=rreq.attempt, tokens=len(rreq.tokens))
        rreq.root_span = None

    def _finalize(self, rreq: RouterRequest, reason: Optional[str]):
        rreq.state, rreq.finish_reason = rq.FINISHED, reason
        rreq.finish_ts = self.clock()
        rreq.proxy = None
        self._close_attempt(rreq, "finished")
        self._close_root(rreq)
        self.requests.pop(rreq.request_id, None)
        self.finished.append(rreq)
        self._counters["finished"] += 1
        self._done_this_step.append(rreq)
        self._emit("request.finish", request_id=rreq.request_id,
                   replica=rreq.replica, failovers=rreq.attempt,
                   new_tokens=len(rreq.tokens), reason=reason)

    def _shed(self, rreq: RouterRequest, reason: str) -> RouterRequest:
        rreq.state, rreq.finish_reason = rq.SHED, reason
        rreq.finish_ts = self.clock()
        rreq.proxy = None
        self._close_attempt(rreq, f"shed:{reason}")
        self._close_root(rreq)
        # identity check: shedding a duplicate-id submit must not evict
        # the live original that owns the slot in the registry
        if self.requests.get(rreq.request_id) is rreq:
            del self.requests[rreq.request_id]
        self.finished.append(rreq)
        self._counters["shed"] += 1
        reasons = self._counters["shed_reasons"]
        reasons[reason] = reasons.get(reason, 0) + 1
        self._done_this_step.append(rreq)
        self._emit("request.shed", request_id=rreq.request_id,
                   reason=reason, tier=self.tier)
        return rreq

    # ------------------------------------------------------------------
    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Client-side cancel of one live request (the gateway's
        disconnect / slow-reader seam): cancels the replica-side proxy
        through the engine's ``cancel()`` so the decode slot and its KV
        blocks free immediately, then sheds the client handle. Returns
        False when the id is unknown or already terminal."""
        rreq = self.requests.get(request_id)
        if rreq is None or rreq.done:
            return False
        if rreq.proxy is not None and 0 <= rreq.replica < len(self.replicas):
            replica_cancel = getattr(self.replicas[rreq.replica],
                                     "cancel", None)
            if replica_cancel is not None:
                replica_cancel(rreq.proxy.request_id, reason)
            self._assigned[rreq.replica].discard(request_id)
        self._shed(rreq, reason)
        return True

    # ------------------------------------------------------------------
    # failure handling + failover
    def _replica_failed(self, idx: int, reason: str, fatal: bool):
        h = self.health[idx]
        if fatal:
            h.record_crash(reason)
        else:
            h.record_failure(reason)
        if idx in self._probe_req and not h.probing:
            # the probe request was in flight when the failure landed;
            # it fails over (or dies) with the rest of the assignment
            del self._probe_req[idx]
        if not h.routable and h.state != DRAINING:
            self._failover_replica(idx, reason)
        elif (h.state == DRAINING and h.consecutive_failures
              >= self.config.failure_threshold):
            # a draining replica that can no longer step must yield its
            # in-flight work: drain-in-place defers to liveness, or
            # drain() would spin on requests that can never finish
            self._failover_replica(idx, f"drain:{reason}")

    def _failover_replica(self, idx: int, reason: str):
        """Reroute everything in flight on a tripped/dead replica. With
        migration on and the source pool still readable (TRIPPED/stalled
        /DRAINING — anything short of a hard crash) each sequence's
        committed KV MOVES to a survivor and decoding resumes mid-stream
        with zero prefill dispatches; otherwise deterministic replay
        makes the reroute transparent: the survivor regenerates the
        greedy stream from the full prompt and the shim dedupes
        already-delivered positions."""
        rids = sorted(self._assigned[idx])
        self._assigned[idx].clear()
        self._probe_req.pop(idx, None)
        cancel = getattr(self.replicas[idx], "cancel", None)
        now = self.clock()
        consumer = "drain" if reason.startswith("drain") else "failover"
        for rid in rids:
            rreq = self.requests.get(rid)
            if rreq is None:
                continue
            # migrate-first: a hard crash (DEAD — pool unreadable) keeps
            # the replay path; anything else moves the state instead of
            # redoing the work
            mig = "off"
            if self.health[idx].state != DEAD:
                mig = self._migrate_request(rreq, idx, now, reason,
                                            consumer=consumer)
            if mig == "ok":
                continue
            if rreq.proxy is not None and cancel is not None:
                # best-effort: release the abandoned proxy's decode slot
                # and KV blocks so a replica that later recovers through
                # a half-open probe is not haunted by zombie decodes
                try:
                    cancel(rreq.proxy.request_id, "failover")
                except Exception:
                    pass
            self._close_attempt(rreq, f"failover:{reason}")
            rreq.attempt += 1
            self._counters["failovers"] += 1
            self._emit("failover", request_id=rid, from_replica=idx,
                       reason=reason, attempt=rreq.attempt,
                       delivered=len(rreq.tokens))
            if rreq.attempt > self.config.max_failovers:
                self._shed(rreq, "replica_lost")
                continue
            if rreq.tokens and self._sampling(idx) and not rreq.keyed:
                # the delivered prefix was UNSEEDED-sampled — no survivor
                # can regenerate it bit-identically, so the replay-splice
                # contract is unsatisfiable. With migration available
                # the KV (and the sampling counters) would have MOVED;
                # reaching here means the move was attempted and failed
                # (`migration_failed` — a fault) or was never possible
                # (`nondeterministic_replay` — policy): dashboards must
                # tell the two apart, so shed loudly with the reason
                # split instead of streaming a garbled continuation. A
                # KEYED prefix is regenerable from (seed, position) by
                # any survivor, so it falls through to replay below.
                self._shed(rreq, "migration_failed" if mig == "failed"
                           else "nondeterministic_replay")
                continue
            self._dispatch(rreq, now, exclude={idx})

    def _migrate_request(self, rreq: RouterRequest, src_idx: int,
                         now: float, reason: str,
                         consumer: str = "failover") -> str:
        """Try to MOVE one in-flight request's committed KV off
        ``src_idx`` onto the best candidate replica. Returns ``"ok"``
        (target committed; proxy/assignment/attempt subtree swapped),
        ``"off"`` (migration disabled for ``consumer``, or structurally
        impossible — no export/import surface, queued-only work, no
        candidate), or ``"failed"`` (attempted, fell through — the
        caller replays)."""
        mig = self._migrator
        if (mig is None or not mig.allows(consumer)
                or rreq.proxy is None
                or not hasattr(self.replicas[src_idx], "export_sequence")):
            return "off"
        deadline_ms = None
        if rreq.deadline_ms:
            # same contract as dispatch: the client's deadline does not
            # restart on a move — hand the target the REMAINING budget
            deadline_ms = rreq.deadline_ms - 1e3 * (now - rreq.submit_ts)
            if deadline_ms <= 0:
                return "off"  # the deadline sweep/shed path owns this
        tgt = next((i for i in self._candidates(now, exclude={src_idx})
                    if hasattr(self.replicas[i], "import_sequence")
                    and self._sampling(i) == self._sampling(src_idx)),
                   None)
        if tgt is None:
            return "off"
        new_span = ictx = None
        if self._tracer.enabled:
            new_span = self._tracer.begin(
                "attempt", rreq.trace_id, parent=span_id(rreq.root_span),
                start_ns=to_ns(now), attempt=rreq.attempt + 1,
                replica=tgt, migrated=True)
            ictx = trace_ctx(rreq.trace_id, parent=span_id(new_span),
                             attempt=rreq.attempt + 1)
        info = mig.migrate(
            self.replicas[src_idx], self.replicas[tgt],
            rreq.proxy.request_id,
            import_id=f"{rreq.request_id}#a{rreq.attempt + 1}",
            deadline_ms=deadline_ms, stream=self._shim(rreq),
            trace=rreq.trace_id, parent=span_id(rreq.root_span),
            import_trace=ictx, src=src_idx, dst=tgt, reason=reason)
        if info is None:
            end_span(new_span, end_ns=to_ns(self.clock()),
                     outcome="migrate_failed")
            return "failed"
        self._assigned[src_idx].discard(rreq.request_id)
        if self._probe_req.get(src_idx) == rreq.request_id:
            del self._probe_req[src_idx]
        self._close_attempt(rreq, f"migrate:{reason}")
        rreq.attempt += 1
        rreq.proxy, rreq.replica = info["request"], tgt
        rreq.state = rq.RUNNING if rreq.tokens else rq.QUEUED
        self._assigned[tgt].add(rreq.request_id)
        rreq.attempt_span = new_span
        rreq.attempt_start_pos = len(rreq.tokens)
        rreq.deliver_t0 = rreq.deliver_t1 = None
        self._counters["migrations"] += 1
        self._emit("migrate", request_id=rreq.request_id,
                   from_replica=src_idx, to_replica=tgt, reason=reason,
                   attempt=rreq.attempt, blocks=info["blocks"],
                   wire_bytes=info["wire_bytes"],
                   delivered=len(rreq.tokens))
        return "ok"

    def migrate_work(self, idx: int, reason: str = "drain",
                     consumer: str = "drain", limit: int = 0) -> int:
        """Migrate replica ``idx``'s in-flight work to survivors (the
        fleet manager's drain-via-migration and rebalance entry point).
        Returns how many requests moved; work that cannot move stays
        put — the caller's fallback (drain timeout, yield) still owns
        it. ``limit`` bounds one sweep (0 = everything)."""
        if self._migrator is None or not self._migrator.allows(consumer):
            return 0
        moved = 0
        now = self.clock()
        for rid in sorted(self._assigned[idx]):
            if limit and moved >= limit:
                break
            rreq = self.requests.get(rid)
            if rreq is None or rreq.proxy is None:
                continue
            if self._migrate_request(rreq, idx, now, reason,
                                     consumer=consumer) == "ok":
                moved += 1
        return moved

    # ------------------------------------------------------------------
    # soft health + degradation ladder
    def _observe_health(self):
        c = self.config
        if c.degraded_ttft_ms <= 0 and c.degraded_shed_rate <= 0:
            return
        for idx, h in enumerate(self.health):
            if h.state not in (HEALTHY, DEGRADED):
                continue
            try:
                st = self.replicas[idx].stats()
            except Exception:
                continue
            h.observe(ttft_p95_ms=st.get("ttft_ms_p95"),
                      shed_rate=st.get("shed_rate"))

    def overload(self) -> float:
        """Aggregate queue pressure over routable replicas (1.0 when none
        are routable — total overload by definition)."""
        depth = cap = 0
        for idx, h in enumerate(self.health):
            if not h.routable:
                continue
            g = self._gauges(idx)
            depth += int(g.get("queue_depth", 0))
            cap += int(g.get("queue_capacity", 0))
        if cap <= 0:
            return 1.0
        return depth / cap

    def _evaluate_ladder(self):
        c = self.config
        score = self.overload()
        n = len(c.ladder_enter)
        while self.tier < n and score >= c.ladder_enter[self.tier]:
            self._set_tier(self.tier + 1, score)
        if (self.tier > 0 and score <= c.ladder_exit[self.tier - 1]
                and self._step_count - self._tier_changed_step
                >= c.ladder_dwell_steps):
            self._set_tier(self.tier - 1, score)

    def _set_tier(self, tier: int, score: float):
        old, self.tier = self.tier, tier
        self._tier_changed_step = self._step_count
        self._counters["tier_transitions"] += 1
        self._emit("tier", from_tier=old, to_tier=tier,
                   score=round(score, 4))

    # ------------------------------------------------------------------
    # rolling restarts + fleet seams
    def start_drain(self, idx: int):
        """Stop routing new work to replica ``idx``; in-flight requests
        finish in place (a ``replica.drained`` event fires when the last
        one does). Idempotent: a repeat call on an already-DRAINING
        replica changes nothing — and in particular must not clear an
        in-flight half-open probe's bookkeeping."""
        if self.health[idx].state == DRAINING:
            return
        self.health[idx].start_drain()
        self._probe_req.pop(idx, None)

    def reactivate(self, idx: int, replica=None):
        """Bring a drained/tripped/dead replica back into rotation —
        optionally swapping in a fresh engine object (the restarted
        process). A LIVE replica (HEALTHY/DEGRADED) is refused loudly:
        silently swapping an engine that is still taking traffic would
        discard its in-flight work's home — ``start_drain()`` it first."""
        h = self.health[idx]
        if h.state in (HEALTHY, DEGRADED):
            raise ValueError(
                f"replica {idx} is live ({h.state}) — reactivate() only "
                f"brings back a draining/tripped/dead/parked replica; "
                f"start_drain({idx}) first to swap a serving engine")
        if replica is not None:
            if self._assigned[idx]:
                # the old engine is being discarded with work still on
                # it: fail the work over BEFORE the swap (cancel must
                # reach the old engine) or drain() would poll orphaned
                # proxies forever
                self._failover_replica(idx, "reactivate")
            self.replicas[idx] = replica
        self.health[idx].reactivate()

    def add_replica(self, replica) -> int:
        """Grow the fleet: append a fresh replica (HEALTHY, immediately
        routable) and return its index. The fleet manager's cold
        scale-up path; also usable directly for manual capacity adds."""
        idx = len(self.replicas)
        self.replicas.append(replica)
        self.health.append(ReplicaHealth(self.config, idx, self.clock,
                                         emit=self._emit))
        self._assigned.append(set())
        self._emit("replica.added", replica=idx)
        return idx

    def assigned(self, idx: int) -> int:
        """In-flight requests currently assigned to replica ``idx`` (the
        public drain-progress gauge — a DRAINING replica is drained when
        this reaches zero)."""
        return len(self._assigned[idx])

    def yield_work(self, idx: int, reason: str = "yield"):
        """Fail replica ``idx``'s in-flight work over to survivors
        without a health verdict — the drain-timeout escape hatch: a
        wedged drain must never deadlock ``drain()`` behind one
        replica."""
        if self._assigned[idx]:
            self._failover_replica(idx, reason)

    def fleet_gauges(self) -> dict:
        """One merged fleet view from the public surfaces: per-state
        replica counts, aggregate queue/slot gauges over alive replicas,
        and the overload score — the payload of the ``fleet`` gauge
        event and the capacity model's food."""
        by_state = {s: 0 for s in STATES}
        depth = cap = busy = total = 0
        for idx, h in enumerate(self.health):
            by_state[h.state] += 1
            if not h.alive:
                continue
            g = self._gauges(idx)
            depth += int(g.get("queue_depth", 0))
            cap += int(g.get("queue_capacity", 0))
            busy += int(g.get("slots_busy", 0))
            total += int(g.get("slots_total", 0))
        return {
            "replicas": len(self.replicas),
            "routable": sum(1 for h in self.health if h.routable),
            "by_state": by_state,
            "queue_depth": depth, "queue_capacity": cap,
            "slots_busy": busy, "slots_total": total,
            "live_requests": len(self.requests),
            "overload": round(self.overload(), 4),
        }

    # ------------------------------------------------------------------
    @property
    def pending(self) -> bool:
        return bool(self.requests)

    def drain(self, max_steps: Optional[int] = None) -> List[RouterRequest]:
        out: List[RouterRequest] = []
        steps = 0
        while self.pending and (max_steps is None or steps < max_steps):
            out.extend(self.step())
            steps += 1
        return out

    def generate_batch(self, prompts, max_new_tokens: int = 0, **kwargs):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens, **kwargs)
                for p in prompts]
        self.drain()
        return [r.tokens if r.state == rq.FINISHED else None for r in reqs]

    def reset_stats(self):
        """Counter epoch boundary (bench warmup -> measured window); live
        requests and health state are untouched."""
        self.finished.clear()
        self._counters = {"submitted": 0, "finished": 0, "shed": 0,
                          "failovers": 0, "migrations": 0,
                          "deduped_tokens": 0,
                          "replay_divergence": 0, "tier_transitions": 0,
                          "shed_reasons": {}}

    def stats(self) -> dict:
        s = self._counters
        total = max(1, s["submitted"])
        ttfts = [r.record()["ttft_ms"] for r in self.finished
                 if r.first_token_ts]
        return {
            "tier": self.tier,
            "replica_states": [h.state for h in self.health],
            "breaker_trips": sum(h.trips for h in self.health),
            "finished": s["finished"], "shed": s["shed"],
            "shed_reasons": dict(s["shed_reasons"]),
            "failovers": s["failovers"],
            "migrations": s["migrations"],
            "deduped_tokens": s["deduped_tokens"],
            "replay_divergence": s["replay_divergence"],
            "tier_transitions": s["tier_transitions"],
            "availability": round(s["finished"] / total, 4),
            "ttft_ms_p50": _pct(ttfts, 50),
            "ttft_ms_p95": _pct(ttfts, 95),
            "live": len(self.requests),
        }

    def destroy(self):
        for r in self.replicas:
            destroy = getattr(r, "destroy", None)
            if destroy is not None:
                destroy()


# ---------------------------------------------------------------------------
# fleet manager: elastic scale over the router's drain/reactivate seams


class ReplicaFactory:
    """The scale-up seam: builds one fresh replica engine. ``build()``
    returns anything with the ``ServingEngine`` surface, or raises —
    the fleet manager backs off exponentially on failures (and the
    chaos harness wraps this seam to prove it).

    ``warm`` declares the build path: a warm factory restores the PR 8
    AOT program bundle + ``tuned.json`` at engine build (checkpoint
    ``aot``/``tuning`` blocks), so the new replica reaches first token
    without steady-state compiles; a cold one pays the full compile."""

    warm = False

    def build(self):
        raise NotImplementedError


class CallableReplicaFactory(ReplicaFactory):
    """Wrap a zero-arg builder callable as the factory seam. The warm
    production shape closes over the serving/AOT config::

        CallableReplicaFactory(
            lambda: ServingEngine(init_inference(
                model, serving=serving_cfg,
                tuning={"artifact": "ckpt/tuned.json"},   # PR 8 tunables
                telemetry={"enabled": True})),            # + armed AOTStore
            warm=True)
    """

    def __init__(self, fn: Callable, warm: bool = False):
        self._fn = fn
        self.warm = bool(warm)

    def build(self):
        return self._fn()


class FleetManager:
    """Elastic scale over a :class:`ReplicaRouter`: the execution half
    of the autoscaler (policy in ``serving/autoscaler.py``), walking
    replicas through the router's public ``start_drain`` /
    ``reactivate`` / ``add_replica`` seams.

    Scale-down picks the least-loaded routable replica, drains it in
    place, and **parks** the drained engine — compiled programs and all
    — instead of destroying it. Scale-up walks the cheapest path first:

    1. **cancel an in-progress drain** (the burst-during-scale-down
       case: the replica still holds its work, reactivation is free);
    2. **unpark** a parked engine (warm: its programs are live);
    3. **build** through the :class:`ReplicaFactory` seam — into a DEAD
       slot when one exists, else appended — with exponential backoff
       across factory failures.

    Every decision is a ``fleet`` telemetry event and an ``autoscale``
    span on the request-trace stream. Token delivery is untouched: the
    router's exactly-once dedupe shim owns that contract, and scaling
    only ever uses the same drain/failover paths chaos already proves.
    """

    def __init__(self, router: ReplicaRouter, factory=None, config=None,
                 capacity=None):
        self.router = router
        if config is None:
            config = FleetConfig()
        elif isinstance(config, dict):
            config = FleetConfig(**config)
        self.config: FleetConfig = config
        if callable(factory) and not hasattr(factory, "build"):
            factory = CallableReplicaFactory(factory)
        self.factory = factory
        self.capacity = capacity          # optional CapacityModel feed
        self.clock = router.clock
        self.telemetry = router.telemetry
        # live metrics plane: the telemetry manager's registry when one
        # is armed (FakeTelemetry test doubles carry none — getattr, not
        # attribute reach-in, keeps them working)
        self._metrics = getattr(self.telemetry, "metrics",
                                None) or NULL_REGISTRY
        self.autoscaler = Autoscaler(config)
        self._tracer = router._tracer
        self._trace_id = (self._tracer.new_trace(hint="fleet")
                          if self._tracer.enabled else None)
        self._step_count = 0
        self._parked: Dict[int, object] = {}    # idx -> parked engine
        self._draining: Dict[int, int] = {}     # idx -> drain start step
        self._factory_fails = 0
        self._factory_next_step = 0
        self._last_step_ts = self.clock()
        self._last_rebalance_step: Optional[int] = None
        self._counters = self._fresh_counters()

    @staticmethod
    def _fresh_counters():
        return {"scale_ups": 0, "scale_downs": 0, "parks": 0,
                "unparks": 0, "drains_cancelled": 0, "drains_lost": 0,
                "drain_timeouts": 0, "factory_builds": 0,
                "factory_failures": 0, "drain_migrations": 0,
                "rebalances": 0}

    # ------------------------------------------------------------------
    def _emit(self, name: str, **data):
        self.telemetry.emit("fleet", name, step=self._step_count, **data)

    @property
    def active_size(self) -> int:
        """Replicas currently taking traffic (HEALTHY + DEGRADED)."""
        return sum(1 for h in self.router.health if h.routable)

    @property
    def pending(self) -> bool:
        return self.router.pending

    # ------------------------------------------------------------------
    # front-door delegation (the fleet manager IS the front door once
    # autoscaling is on — same surface, scaling rides step())
    def submit(self, prompt, **kwargs) -> RouterRequest:
        rreq = self.router.submit(prompt, **kwargs)
        if rreq.state == rq.SHED:
            # submit-time sheds never appear in a step() result — feed
            # the budget here or the shed budget undercounts exactly
            # the overload sheds it exists to catch
            self.autoscaler.observe_requests([rreq.record()])
        return rreq

    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        return self.router.cancel(request_id, reason)

    def _routable_load(self) -> float:
        """Per-replica load over ROUTABLE replicas only — the capacity
        model fits one serving replica's curve, so parked/draining
        slots must not dilute the denominator (a saturated survivor
        would read as half-loaded)."""
        busy = depth = total = 0
        for idx, h in enumerate(self.router.health):
            if not h.routable:
                continue
            g = self.router._gauges(idx)
            busy += int(g.get("slots_busy", 0))
            depth += int(g.get("queue_depth", 0))
            total += int(g.get("slots_total", 0))
        return (busy + depth) / max(1, total)

    def step(self) -> List[RouterRequest]:
        done = self.router.step()
        self._step_count += 1
        self._check_drains()
        # a drain-timeout yield can fail work over — and shed it — AFTER
        # the router snapshotted its step result: pick those terminals
        # up, or the shed budget misses exactly the overload sheds it
        # exists to catch (and drain() callers never see them)
        live = self.router._done_this_step
        if len(live) > len(done):
            done = done + live[len(done):]
        overload = self.router.overload()
        self.autoscaler.observe_requests(r.record() for r in done)
        self.autoscaler.observe_step(overload)
        if self.capacity is not None:
            now = self.clock()
            dt = max(0.0, now - self._last_step_ts)
            self._last_step_ts = now
            tokens = sum(len(r.tokens) for r in done
                         if r.state == rq.FINISHED)
            active = max(1, self.active_size)
            load = self._routable_load()
            self.capacity.observe(load, tokens=tokens / active, secs=dt)
            for r in done:
                if r.state == rq.FINISHED and r.first_token_ts:
                    self.capacity.observe(load, ttft_ms=1e3 * (
                        r.first_token_ts - r.submit_ts))
        if self.active_size > self.config.max_replicas \
                and not self._draining:
            # breaker recovery can push the routable count past the
            # bound (a scale-up replaced tripped replicas that later
            # probed back HEALTHY): max_replicas is a hard ceiling, not
            # a hint — drain the excess, one replica per step
            self._execute(Decision(SCALE_DOWN, "max_replicas",
                                   self._step_count, overload=overload))
        else:
            decision = self.autoscaler.decide(
                self.active_size, overload=overload,
                can_shrink=not self._draining)
            if decision is not None:
                self._execute(decision)
        self._maybe_rebalance()
        if self.telemetry.enabled:
            self._emit("fleet.gauges", **self.gauges())
            self._metrics_step(overload)
        return done

    def _metrics_step(self, overload: float):
        """Per-step registry feed: per-replica health (one-hot), fleet
        state counts, load/overload, and the autoscaler's error-budget
        internals (burn rates + budget remaining) — the policy's math
        made externally scrapeable. No-op instruments when the metrics
        plane is disarmed."""
        m = self._metrics
        health = m.gauge("ds_replica_health", ("replica", "state"),
                         max_label_sets=256)
        for idx, h in enumerate(self.router.health):
            for state in STATES:
                health.labels(replica=str(idx), state=state).set(
                    1 if h.state == state else 0)
        by_state = {s: 0 for s in STATES}
        for h in self.router.health:
            by_state[h.state] += 1
        fleet = m.gauge("ds_fleet_replicas", ("state",))
        for state, n in by_state.items():
            fleet.labels(state=state).set(n)
        m.gauge("ds_fleet_active_replicas").set(self.active_size)
        m.gauge("ds_fleet_parked_replicas").set(len(self._parked))
        m.gauge("ds_fleet_draining_replicas").set(len(self._draining))
        m.gauge("ds_fleet_overload").set(round(float(overload), 4))
        m.gauge("ds_fleet_load").set(round(self._routable_load(), 4))
        budget = m.gauge("ds_slo_budget_remaining", ("slo",))
        for slo, rem in self.autoscaler.budget_remaining().items():
            if rem is not None:
                budget.labels(slo=slo).set(rem)
        burn = m.gauge("ds_slo_burn_rate", ("slo", "window"))
        for slo, windows in self.autoscaler.burn_rates().items():
            for window, rate in windows.items():
                if rate is not None:
                    burn.labels(slo=slo, window=window).set(
                        round(min(rate, 1e6), 4))

    def drain(self, max_steps: Optional[int] = None) -> List[RouterRequest]:
        out: List[RouterRequest] = []
        steps = 0
        while self.pending and (max_steps is None or steps < max_steps):
            out.extend(self.step())
            steps += 1
        return out

    def generate_batch(self, prompts, max_new_tokens: int = 0, **kwargs):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens, **kwargs)
                for p in prompts]
        self.drain()
        return [r.tokens if r.state == rq.FINISHED else None for r in reqs]

    # ------------------------------------------------------------------
    # scaling
    def _check_drains(self):
        c = self.config
        for idx in sorted(self._draining):
            h = self.router.health[idx]
            if h.state == DEAD:
                # chaos (or reality) killed the replica mid-drain: the
                # router already failed its work over exactly-once —
                # the slot is simply lost, not parked
                self._draining.pop(idx)
                self._counters["drains_lost"] += 1
                self._emit("drain.lost", replica=idx)
                continue
            if self.router.assigned(idx):
                # drain-via-migration: MOVE the in-flight work to
                # survivors instead of waiting it out — the timeout
                # below demotes from the plan to the fallback. Work
                # that cannot move (no capacity, mid-prefill, fault)
                # stays put and keeps draining in place.
                moved = self.router.migrate_work(idx, "drain")
                if moved:
                    self._counters["drain_migrations"] += moved
                    self._emit("drain.migrated", replica=idx,
                               moved=moved)
            if self.router.assigned(idx) == 0:
                self._park(idx)
                continue
            age = self._step_count - self._draining[idx]
            if c.drain_timeout_steps and age >= c.drain_timeout_steps:
                # a wedged drain must not hold the scale-down hostage:
                # yield the stragglers to survivors and park anyway
                self.router.yield_work(idx, "drain_timeout")
                self._counters["drain_timeouts"] += 1
                self._emit("drain.timeout", replica=idx, steps=age)
                self._park(idx)

    def _maybe_rebalance(self):
        """Migrate-based decode-side defragmentation: when the most
        fragmented routable replica's ``kv_fragmentation`` gauge (the
        PR 14 pool-waste signal — reserved-but-uncommitted token rows
        over reserved capacity) crosses ``rebalance_fragmentation``,
        move up to ``rebalance_max_requests`` of its sequences to
        less-fragmented survivors, then cool down — one bounded sweep
        per ``rebalance_cooldown_steps``, never a migration storm."""
        c = self.config
        if not c.rebalance_fragmentation:
            return
        if (self._last_rebalance_step is not None
                and self._step_count - self._last_rebalance_step
                < c.rebalance_cooldown_steps):
            return
        worst, frag = None, 0.0
        for idx, h in enumerate(self.router.health):
            if not h.routable or self.router.assigned(idx) == 0:
                continue
            f = float(self.router._gauges(idx).get("kv_fragmentation",
                                                   0.0))
            if f > frag:
                worst, frag = idx, f
        if worst is None or frag < c.rebalance_fragmentation:
            return
        # cooldown stamps on TRIGGER, not on success: a rebalance whose
        # every move fell through must back off, not hammer every step
        self._last_rebalance_step = self._step_count
        moved = self.router.migrate_work(
            worst, "rebalance", consumer="rebalance",
            limit=c.rebalance_max_requests)
        if moved:
            self._counters["rebalances"] += moved
            self._emit("rebalance", replica=worst,
                       fragmentation=round(frag, 4), moved=moved)

    def _park(self, idx: int):
        self._draining.pop(idx, None)
        self._parked[idx] = self.router.replicas[idx]
        self._counters["parks"] += 1
        self._emit("replica.parked", replica=idx)

    def _execute(self, decision):
        t0 = self.clock()
        before = self.active_size
        if decision.action == SCALE_UP:
            detail = self._scale_up(decision.reason)
        else:
            detail = self._scale_down(decision.reason)
        if detail is None:
            return
        detail.setdefault("burn", decision.burn)
        detail["overload"] = decision.overload
        self._emit(f"scale.{decision.action}", reason=decision.reason,
                   from_size=before, to_size=self.active_size, **detail)
        self._metrics.counter("ds_fleet_scale_events_total",
                              ("action",)).labels(
                                  action=decision.action).inc()
        if self._tracer.enabled:
            self._tracer.record_span(
                "autoscale", self._trace_id, to_ns(t0),
                to_ns(self.clock()), action=decision.action,
                reason=decision.reason, from_size=before,
                to_size=self.active_size,
                source=detail.get("source"))

    def _scale_up(self, reason: str) -> Optional[dict]:
        # 1) cheapest: cancel an in-progress drain (work still in place)
        for idx in sorted(self._draining):
            if self.router.health[idx].state == DRAINING:
                self._draining.pop(idx)
                self.router.reactivate(idx)
                self._counters["drains_cancelled"] += 1
                self._counters["scale_ups"] += 1
                return {"source": "cancelled_drain", "replica": idx,
                        "warm": True}
        # 2) warm: unpark a drained engine — compiled programs live
        if self._parked:
            idx = min(self._parked)
            self._parked.pop(idx)
            self.router.reactivate(idx)
            self._counters["unparks"] += 1
            self._counters["scale_ups"] += 1
            return {"source": "parked", "replica": idx, "warm": True}
        # 3) the factory seam, behind exponential failure backoff
        if self.factory is None:
            self._emit("scale.blocked", reason=reason,
                       detail="no_factory")
            return None
        if self._step_count < self._factory_next_step:
            return None  # backing off a failing factory; retry later
        try:
            replica = self.factory.build()
        except Exception as e:
            self._factory_fails += 1
            backoff = self.config.factory_backoff_steps \
                * (2 ** (self._factory_fails - 1))
            self._factory_next_step = self._step_count + backoff
            self._counters["factory_failures"] += 1
            self._emit("factory.failed", error=f"{type(e).__name__}: {e}",
                       failures=self._factory_fails,
                       retry_step=self._factory_next_step)
            return None
        self._factory_fails = 0
        self._factory_next_step = 0
        self._counters["factory_builds"] += 1
        self._counters["scale_ups"] += 1
        dead = next((i for i, h in enumerate(self.router.health)
                     if h.state == DEAD and i not in self._parked), None)
        if dead is not None:
            self.router.reactivate(dead, replica=replica)
            return {"source": "factory", "replica": dead,
                    "warm": bool(self.factory.warm), "replaced_dead": True}
        idx = self.router.add_replica(replica)
        return {"source": "factory", "replica": idx,
                "warm": bool(self.factory.warm)}

    def _scale_down(self, reason: str) -> Optional[dict]:
        routable = [i for i, h in enumerate(self.router.health)
                    if h.routable]
        if len(routable) <= self.config.min_replicas:
            return None
        # least-loaded victim (highest index breaks ties): draining the
        # emptiest replica finishes fastest
        idx = min(routable, key=lambda i: (self.router._load(i), -i))
        self.router.start_drain(idx)
        self._draining[idx] = self._step_count
        self._counters["scale_downs"] += 1
        return {"source": "drain", "replica": idx}

    # manual operator levers (the tests' chaos choreography too)
    def scale_up(self, reason: str = "manual") -> Optional[dict]:
        before = self.active_size
        detail = self._scale_up(reason)
        if detail is not None:
            self._emit("scale.up", reason=reason, from_size=before,
                       to_size=self.active_size, **detail)
        return detail

    def scale_down(self, idx: Optional[int] = None,
                   reason: str = "manual") -> Optional[dict]:
        if idx is None:
            before = self.active_size
            detail = self._scale_down(reason)
            if detail is not None:
                self._emit("scale.down", reason=reason, from_size=before,
                           to_size=self.active_size, **detail)
            return detail
        if idx in self._draining:
            return None  # idempotent, like start_drain itself
        before = self.active_size
        self.router.start_drain(idx)
        self._draining[idx] = self._step_count
        self._counters["scale_downs"] += 1
        self._emit("scale.down", reason=reason, replica=idx,
                   from_size=before, to_size=self.active_size)
        return {"source": "drain", "replica": idx}

    # ------------------------------------------------------------------
    def gauges(self) -> dict:
        """The merged per-step fleet view (also the ``fleet.gauges``
        event payload): router fleet gauges + fleet bookkeeping + SLO
        budget remaining."""
        return {
            **self.router.fleet_gauges(),
            "active": self.active_size,
            "parked": len(self._parked),
            "draining": len(self._draining),
            "min_replicas": self.config.min_replicas,
            "max_replicas": self.config.max_replicas,
            "budget_remaining": self.autoscaler.budget_remaining(),
        }

    def stats(self) -> dict:
        return {
            **self.gauges(),
            **self._counters,
            "router": self.router.stats(),
        }

    def reset_stats(self):
        self._counters = self._fresh_counters()
        self.router.reset_stats()

    def destroy(self):
        # parked engines stay in router.replicas (parking is fleet-level
        # bookkeeping, not removal), so the router teardown covers them
        self.router.destroy()
