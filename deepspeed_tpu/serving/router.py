"""Resilient multi-replica front door: health-aware routing, failover
with deterministic replay, and an SLO-guarded degradation ladder.

Pure host-side policy (no jax imports — the tier-1 tests drive it with
fake replicas in milliseconds). A *replica* is anything with the
``ServingEngine`` surface: ``submit(prompt, max_new_tokens, request_id,
eos_token_id, deadline_ms, stream)`` returning a live ``Request``,
``step()``, ``gauges()`` and ``stats()``. The router composes N of them
behind one ``submit()``/``step()``/``drain()`` surface:

- **routing** — each submit goes to the least-loaded routable replica
  (load = ``queue_depth + slots_busy`` from the public ``gauges()``
  payload, the same numbers the per-step serving telemetry events
  carry): HEALTHY replicas first, DEGRADED only when no HEALTHY peer
  can take it, plus at most one half-open probe to a TRIPPED replica
  whose backoff elapsed.
- **failover with deterministic replay** — greedy decode is
  bit-reproducible (the PR 4 batch-invariance guarantee), so when a
  replica dies or its breaker trips the router resubmits every one of
  its in-flight requests — full prompt, the ORIGINAL effective
  ``max_new_tokens`` — to a survivor and dedupes the regenerated stream
  by position: tokens the client already saw are swallowed (and checked
  — a mismatch is a loud ``replay.divergence`` event, the greedy
  contract broken), new positions stream exactly once. The client sees
  one uninterrupted exactly-once token stream, not a restart.
- **degradation ladder** — under aggregate overload (queue depth over
  capacity across routable replicas) the router walks explicit tiers
  instead of collapsing into timeout storms: full service -> clamp
  ``max_new_tokens`` -> shed below-priority-floor work -> brownout
  (smallest-bucket prompts only). Tier entry is immediate; exit needs
  the score below the (lower) exit threshold for ``ladder_dwell_steps``
  — the hysteresis guard.

Every transition — replica state, breaker trip/probe/close, failover,
tier — is a ``router`` telemetry event on the unified stream
(rendered by ``tools/telemetry_report.py``).
"""

import dataclasses
import itertools
import math
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set

from deepspeed_tpu.serving import request as rq
from deepspeed_tpu.serving.config import RouterConfig
from deepspeed_tpu.serving.health import (DEAD, DEGRADED, DRAINING, HEALTHY,
                                          TRIPPED, ReplicaHealth)
from deepspeed_tpu.telemetry.tracing import (NULL_TRACER, end_span, span_id,
                                             to_ns, trace_ctx)

_ids = itertools.count()


@dataclasses.dataclass
class RouterRequest:
    """The client's handle: mirrors ``Request`` (state / tokens / stream)
    but survives its replica. ``tokens`` holds exactly the tokens the
    client's stream callback saw, in order — across any number of
    failovers, each position exactly once."""

    prompt: List[int]
    max_new_tokens: int = 0       # effective budget, pinned at first dispatch
    request_id: str = ""
    priority: int = 0             # ladder tier 2+ sheds below the floor
    eos_token_id: int = -1
    deadline_ms: float = 0.0
    stream: Optional[Callable] = None

    # ---- runtime state (owned by the router) ----
    clamp_budget: int = 0         # tier-1 cap pending default resolution
    state: str = rq.QUEUED
    finish_reason: Optional[str] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    replica: int = -1             # current assignment
    attempt: int = 0              # failovers so far
    proxy: Optional[rq.Request] = None
    submit_ts: float = 0.0
    first_token_ts: float = 0.0
    finish_ts: float = 0.0
    # ---- span tracing (telemetry/tracing.py; None with tracing off) ----
    trace_id: Optional[str] = None     # ONE trace across every failover
    root_span: Optional[object] = None     # open `request` root handle
    attempt_span: Optional[object] = None  # open `attempt` subtree handle
    attempt_start_pos: int = 0    # first NEW position this attempt streams
    # first/last delivery time this attempt (None = nothing delivered
    # yet: a fake clock's legitimate t=0.0 must not read as unset)
    deliver_t0: Optional[float] = None
    deliver_t1: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def done(self) -> bool:
        return self.state in (rq.FINISHED, rq.SHED)

    def record(self) -> dict:
        return {
            "request_id": self.request_id, "state": self.state,
            "reason": self.finish_reason, "prompt_len": self.prompt_len,
            "new_tokens": len(self.tokens), "failovers": self.attempt,
            "ttft_ms": round(1e3 * (self.first_token_ts - self.submit_ts), 3)
            if self.first_token_ts else None,
        }


def _pct(values, q: float):
    if not values:
        return None
    vs = sorted(values)
    k = min(len(vs) - 1, max(0, math.ceil(q / 100.0 * len(vs)) - 1))
    return round(float(vs[k]), 3)


class _NullTelemetry:
    enabled = False

    def emit(self, *a, **k):
        pass


class ReplicaRouter:
    def __init__(self, replicas, config=None, clock=time.monotonic,
                 telemetry=None):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.replicas = list(replicas)
        if config is None:
            config = RouterConfig()
        elif isinstance(config, dict):
            config = RouterConfig(**config)
        self.config: RouterConfig = config
        self.clock = clock
        self.telemetry = (telemetry
                          or getattr(self.replicas[0], "telemetry", None)
                          or _NullTelemetry())
        # span tracer: client-side request traces (root + per-dispatch
        # attempt subtrees + exactly-once deliver spans). A failover
        # continues the SAME trace on the survivor — the replicas join
        # it through the context stamped on their proxy requests.
        self._tracer = (getattr(self.telemetry, "tracer", None)
                        or NULL_TRACER)
        self.health = [ReplicaHealth(config, i, clock, emit=self._emit)
                       for i in range(len(self.replicas))]
        self.tier = 0
        self._tier_changed_step = 0
        self._step_count = 0
        self.requests: Dict[str, RouterRequest] = {}   # live client requests
        self._assigned: List[Set[str]] = [set() for _ in self.replicas]
        self._probe_req: Dict[int, str] = {}           # replica -> request id
        self._done_this_step: List[RouterRequest] = []
        self.finished = deque(maxlen=1024)
        self._counters = {"submitted": 0, "finished": 0, "shed": 0,
                          "failovers": 0, "deduped_tokens": 0,
                          "replay_divergence": 0, "tier_transitions": 0,
                          "shed_reasons": {}}

    # ------------------------------------------------------------------
    def _emit(self, name: str, **data):
        self.telemetry.emit("router", name, step=self._step_count, **data)

    def _gauges(self, idx: int) -> dict:
        try:
            return self.replicas[idx].gauges()
        except Exception:
            return {}

    def _load(self, idx: int) -> int:
        g = self._gauges(idx)
        return int(g.get("queue_depth", 0)) + int(g.get("slots_busy", 0))

    def _sampling(self, idx: int) -> bool:
        return bool(getattr(getattr(self.replicas[idx], "config", None),
                            "do_sample", False))

    def _smallest_bucket(self) -> Optional[int]:
        sizes = [min(b) for r in self.replicas
                 for b in [getattr(r, "buckets", None)] if b]
        return min(sizes) if sizes else None

    # ------------------------------------------------------------------
    # routing
    def _candidates(self, now: float, exclude=()) -> List[int]:
        """Routable replicas in preference order — HEALTHY by load, then
        DEGRADED by load, then TRIPPED replicas whose half-open probe
        window is open (each takes exactly one request)."""
        healthy, degraded, probes = [], [], []
        for i, h in enumerate(self.health):
            if i in exclude:
                continue
            if h.state == HEALTHY:
                healthy.append(i)
            elif h.state == DEGRADED:
                degraded.append(i)
            elif h.can_probe(now) and i not in self._probe_req:
                probes.append(i)
        return (sorted(healthy, key=self._load)
                + sorted(degraded, key=self._load) + sorted(probes))

    def submit(self, prompt, max_new_tokens: int = 0, priority: int = 0,
               request_id: Optional[str] = None, eos_token_id: int = -1,
               deadline_ms: float = 0.0,
               stream: Optional[Callable] = None) -> RouterRequest:
        """Route one request to a replica (non-blocking). The returned
        handle's ``state`` is ``queued`` on success, or ``shed`` with a
        ``finish_reason`` when the degradation ladder or every routable
        replica rejected it."""
        now = self.clock()
        rreq = RouterRequest(
            prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            request_id=request_id or f"rr-{next(_ids)}",
            priority=int(priority), eos_token_id=int(eos_token_id),
            deadline_ms=float(deadline_ms), stream=stream)
        rreq.submit_ts = now
        self._counters["submitted"] += 1
        if self._tracer.enabled:
            rreq.trace_id = self._tracer.new_trace(hint=rreq.request_id)
            rreq.root_span = self._tracer.begin(
                "request", rreq.trace_id, start_ns=to_ns(now),
                request_id=rreq.request_id, prompt_len=rreq.prompt_len)
        if rreq.request_id in self.requests:
            return self._shed(rreq, "duplicate_id")
        # ---- degradation ladder admission ----
        c = self.config
        if self.tier >= 1:
            if rreq.max_new_tokens > 0:
                rreq.max_new_tokens = min(rreq.max_new_tokens,
                                          c.clamp_max_new_tokens)
            else:
                # budget comes from the replica default — cap it at
                # dispatch, once known: the clamp must never RAISE the
                # decode work of a default-budget submit
                rreq.clamp_budget = c.clamp_max_new_tokens
        if self.tier >= 2 and rreq.priority < c.shed_priority_floor:
            return self._shed(rreq, "tier_shed")
        if self.tier >= 3:
            floor = self._smallest_bucket()
            if floor is not None and rreq.prompt_len > floor:
                return self._shed(rreq, "brownout")
        if self._dispatch(rreq, now):
            self.requests[rreq.request_id] = rreq
        return rreq

    def _dispatch(self, rreq: RouterRequest, now: float,
                  exclude=()) -> bool:
        """Try candidates in preference order until one accepts; shed the
        request (last replica-side reason, or ``no_replica``) when none
        does. The effective ``max_new_tokens`` was pinned at first
        dispatch, so a failover replays the exact same decode."""
        last_reason = None
        deadline_ms = rreq.deadline_ms
        if deadline_ms:
            # the client's deadline does not restart on failover: the
            # survivor's scheduler stamps a fresh submit_ts, so hand it
            # only the REMAINING budget — and shed already-over-deadline
            # work instead of replaying it arbitrarily late
            deadline_ms -= 1e3 * (now - rreq.submit_ts)
            if deadline_ms <= 0:
                self._shed(rreq, "deadline")
                return False
        for idx in self._candidates(now, exclude):
            h = self.health[idx]
            probe = h.state == TRIPPED
            if rreq.tokens and self._sampling(idx):
                # the dedupe-splice is only sound across bit-reproducible
                # greedy decodes: a delivered prefix must never resume on
                # a sampling replica (a request with nothing streamed yet
                # is fine — there is nothing to splice)
                last_reason = "nondeterministic_replay"
                continue
            budget = rreq.max_new_tokens
            if budget <= 0 and rreq.clamp_budget:
                # resolve the replica's default budget and cap it (real
                # engines expose it on .config; without one, the cap
                # itself is the degraded-mode budget)
                default = getattr(getattr(self.replicas[idx], "config",
                                          None),
                                  "default_max_new_tokens", 0) or 0
                budget = (min(int(default), rreq.clamp_budget)
                          if default > 0 else rreq.clamp_budget)
            try:
                proxy = self.replicas[idx].submit(
                    rreq.prompt, max_new_tokens=budget,
                    request_id=f"{rreq.request_id}#a{rreq.attempt}",
                    eos_token_id=rreq.eos_token_id,
                    deadline_ms=deadline_ms, stream=self._shim(rreq))
            except Exception as e:
                if probe:
                    # the half-open probe itself failed: it must count
                    # as one (re-trip, backoff doubles) — not as a
                    # first consecutive failure that leaves the probe
                    # window open for immediate hammering
                    h.begin_probe()
                self._replica_failed(
                    idx, f"submit:{type(e).__name__}",
                    fatal=bool(getattr(e, "replica_dead", False)))
                continue
            if proxy.state == rq.SHED:
                last_reason = proxy.finish_reason  # admission said no; next
                continue
            if probe:
                h.begin_probe()
                self._probe_req[idx] = rreq.request_id
            if rreq.max_new_tokens <= 0:
                # pin the effective budget only from an admission that
                # ACCEPTED — the clamp-resolved cap, or the default this
                # replica's proxy reports; a failed candidate's config
                # must not leak into the replay budget
                rreq.max_new_tokens = int(
                    budget or getattr(proxy, "max_new_tokens", 0) or 0)
            rreq.proxy, rreq.replica, rreq.state = proxy, idx, rq.QUEUED
            self._assigned[idx].add(rreq.request_id)
            if self._tracer.enabled:
                # one `attempt` subtree per dispatch; the proxy carries
                # the context so the replica's serve/queue/prefill/
                # decode spans nest under it — ONE trace end to end,
                # failovers included
                rreq.attempt_span = self._tracer.begin(
                    "attempt", rreq.trace_id, parent=span_id(rreq.root_span),
                    start_ns=to_ns(now), attempt=rreq.attempt, replica=idx)
                rreq.attempt_start_pos = len(rreq.tokens)
                rreq.deliver_t0 = rreq.deliver_t1 = None
                proxy.trace = trace_ctx(rreq.trace_id,
                                        parent=span_id(rreq.attempt_span),
                                        attempt=rreq.attempt)
            return True
        self._shed(rreq, last_reason or "no_replica")
        return False

    def _shim(self, rreq: RouterRequest) -> Callable:
        """Per-token dedupe-by-position: the exactly-once guarantee. A
        replayed position must carry the identical token (greedy decode
        is bit-reproducible) — a mismatch is counted and shouted, never
        silently re-streamed."""

        def cb(proxy: rq.Request, tok: int, done: bool):
            if rreq.proxy is not proxy:
                # stale attempt: the request moved on (failed over, or
                # already reported done) — a zombie proxy left decoding
                # on a recovered replica must never resurrect the handle
                # or re-invoke the client stream
                return
            pos = len(proxy.tokens) - 1
            tok = int(tok)
            if pos < len(rreq.tokens):
                self._counters["deduped_tokens"] += 1
                if rreq.tokens[pos] != tok:
                    self._counters["replay_divergence"] += 1
                    self._emit("replay.divergence",
                               request_id=rreq.request_id, position=pos,
                               streamed=rreq.tokens[pos], replayed=tok)
                return
            now = self.clock()
            if not rreq.tokens:
                rreq.first_token_ts = now
            if rreq.deliver_t0 is None:
                rreq.deliver_t0 = now
            rreq.deliver_t1 = now
            rreq.state = rq.RUNNING
            rreq.tokens.append(tok)
            if rreq.stream is not None:
                rreq.stream(rreq, tok, bool(done))

        return cb

    # ------------------------------------------------------------------
    # stepping + health
    def step(self) -> List[RouterRequest]:
        """One router iteration: step every replica that holds work
        (guarded — an exception or stall verdict becomes a health signal
        and a failover), harvest finished/shed proxies, refresh soft
        health from telemetry aggregates, walk the degradation ladder.
        Returns the client requests finished this step."""
        self._step_count += 1
        self._done_this_step = []
        c = self.config
        for idx in range(len(self.replicas)):
            if not self._assigned[idx] or not self.health[idx].alive:
                continue
            t0 = self.clock()
            try:
                self.replicas[idx].step()
            except Exception as e:
                self._replica_failed(
                    idx, f"step:{type(e).__name__}",
                    fatal=bool(getattr(e, "replica_dead", False)))
                continue
            # harvest BEFORE the stall verdict: a slow-but-complete step
            # delivered tokens — requests it finished must not be
            # replayed (or worse, shed) by the failover below
            self._harvest(idx)
            if (c.stall_timeout_secs
                    and self.clock() - t0 >= c.stall_timeout_secs):
                h = self.health[idx]
                h.record_stall("stall")
                self._probe_req.pop(idx, None)
                # DRAINING holds the drain-in-place contract even on a
                # stall verdict (trip() already no-ops there) — mirror
                # the exception path's guard in _replica_failed
                if not h.routable and h.state != DRAINING:
                    self._failover_replica(idx, "stall")
            else:
                self.health[idx].record_success()
        self._observe_health()
        self._evaluate_ladder()
        # snapshot: a later submit-time shed appends to the live list
        # and must not retroactively grow the caller's result
        return list(self._done_this_step)

    def _harvest(self, idx: int):
        for rid in list(self._assigned[idx]):
            rreq = self.requests.get(rid)
            if rreq is None or rreq.proxy is None:
                self._assigned[idx].discard(rid)
                continue
            st = rreq.proxy.state
            if st == rq.FINISHED:
                self._assigned[idx].discard(rid)
                if self._probe_req.get(idx) == rid:
                    del self._probe_req[idx]
                    self.health[idx].probe_success()
                self._finalize(rreq, rreq.proxy.finish_reason)
            elif st == rq.SHED:
                # replica-side policy shed (deadline/queue) — propagate,
                # no failover: resubmitting over-deadline work would feed
                # the very overload the shed relieved
                self._assigned[idx].discard(rid)
                if self._probe_req.get(idx) == rid:
                    del self._probe_req[idx]
                    self.health[idx].probe_inconclusive()
                self._shed(rreq, rreq.proxy.finish_reason or "replica_shed")
        h = self.health[idx]
        if h.state == DRAINING and not self._assigned[idx]:
            self._emit("replica.drained", replica=idx)

    def _close_attempt(self, rreq: RouterRequest, outcome: str):
        """End the open ``attempt`` subtree: a ``deliver`` child records
        exactly the NEW positions this attempt streamed to the client
        (replayed/deduped positions are an attrs counter, never a second
        deliver span — the exactly-once contract, visible in the trace),
        then the attempt span closes with its outcome."""
        if rreq.attempt_span is None:
            return
        now = self.clock()
        delivered = len(rreq.tokens) - rreq.attempt_start_pos
        if delivered > 0:
            t0 = now if rreq.deliver_t0 is None else rreq.deliver_t0
            t1 = now if rreq.deliver_t1 is None else rreq.deliver_t1
            self._tracer.record_span(
                "deliver", rreq.trace_id, to_ns(t0), to_ns(t1),
                parent=span_id(rreq.attempt_span),
                from_pos=rreq.attempt_start_pos, to_pos=len(rreq.tokens),
                tokens=delivered)
        end_span(rreq.attempt_span, end_ns=to_ns(now), outcome=outcome,
                 delivered=delivered)
        rreq.attempt_span = None

    def _close_root(self, rreq: RouterRequest):
        end_span(rreq.root_span, end_ns=to_ns(rreq.finish_ts),
                 state=rreq.state, reason=rreq.finish_reason,
                 failovers=rreq.attempt, tokens=len(rreq.tokens))
        rreq.root_span = None

    def _finalize(self, rreq: RouterRequest, reason: Optional[str]):
        rreq.state, rreq.finish_reason = rq.FINISHED, reason
        rreq.finish_ts = self.clock()
        rreq.proxy = None
        self._close_attempt(rreq, "finished")
        self._close_root(rreq)
        self.requests.pop(rreq.request_id, None)
        self.finished.append(rreq)
        self._counters["finished"] += 1
        self._done_this_step.append(rreq)
        self._emit("request.finish", request_id=rreq.request_id,
                   replica=rreq.replica, failovers=rreq.attempt,
                   new_tokens=len(rreq.tokens), reason=reason)

    def _shed(self, rreq: RouterRequest, reason: str) -> RouterRequest:
        rreq.state, rreq.finish_reason = rq.SHED, reason
        rreq.finish_ts = self.clock()
        rreq.proxy = None
        self._close_attempt(rreq, f"shed:{reason}")
        self._close_root(rreq)
        # identity check: shedding a duplicate-id submit must not evict
        # the live original that owns the slot in the registry
        if self.requests.get(rreq.request_id) is rreq:
            del self.requests[rreq.request_id]
        self.finished.append(rreq)
        self._counters["shed"] += 1
        reasons = self._counters["shed_reasons"]
        reasons[reason] = reasons.get(reason, 0) + 1
        self._done_this_step.append(rreq)
        self._emit("request.shed", request_id=rreq.request_id,
                   reason=reason, tier=self.tier)
        return rreq

    # ------------------------------------------------------------------
    # failure handling + failover
    def _replica_failed(self, idx: int, reason: str, fatal: bool):
        h = self.health[idx]
        if fatal:
            h.record_crash(reason)
        else:
            h.record_failure(reason)
        if idx in self._probe_req and not h.probing:
            # the probe request was in flight when the failure landed;
            # it fails over (or dies) with the rest of the assignment
            del self._probe_req[idx]
        if not h.routable and h.state != DRAINING:
            self._failover_replica(idx, reason)
        elif (h.state == DRAINING and h.consecutive_failures
              >= self.config.failure_threshold):
            # a draining replica that can no longer step must yield its
            # in-flight work: drain-in-place defers to liveness, or
            # drain() would spin on requests that can never finish
            self._failover_replica(idx, f"drain:{reason}")

    def _failover_replica(self, idx: int, reason: str):
        """Reroute everything in flight on a tripped/dead replica.
        Deterministic replay makes this transparent: the survivor
        regenerates the greedy stream from the full prompt and the shim
        dedupes already-delivered positions."""
        rids = sorted(self._assigned[idx])
        self._assigned[idx].clear()
        self._probe_req.pop(idx, None)
        cancel = getattr(self.replicas[idx], "cancel", None)
        now = self.clock()
        for rid in rids:
            rreq = self.requests.get(rid)
            if rreq is None:
                continue
            if rreq.proxy is not None and cancel is not None:
                # best-effort: release the abandoned proxy's decode slot
                # and KV blocks so a replica that later recovers through
                # a half-open probe is not haunted by zombie decodes
                try:
                    cancel(rreq.proxy.request_id, "failover")
                except Exception:
                    pass
            self._close_attempt(rreq, f"failover:{reason}")
            rreq.attempt += 1
            self._counters["failovers"] += 1
            self._emit("failover", request_id=rid, from_replica=idx,
                       reason=reason, attempt=rreq.attempt,
                       delivered=len(rreq.tokens))
            if rreq.attempt > self.config.max_failovers:
                self._shed(rreq, "replica_lost")
                continue
            if rreq.tokens and self._sampling(idx):
                # the delivered prefix was SAMPLED — no survivor can
                # regenerate it bit-identically, so the splice contract
                # is unsatisfiable: fail loudly instead of streaming a
                # garbled continuation of a different sample
                self._shed(rreq, "nondeterministic_replay")
                continue
            self._dispatch(rreq, now, exclude={idx})

    # ------------------------------------------------------------------
    # soft health + degradation ladder
    def _observe_health(self):
        c = self.config
        if c.degraded_ttft_ms <= 0 and c.degraded_shed_rate <= 0:
            return
        for idx, h in enumerate(self.health):
            if h.state not in (HEALTHY, DEGRADED):
                continue
            try:
                st = self.replicas[idx].stats()
            except Exception:
                continue
            h.observe(ttft_p95_ms=st.get("ttft_ms_p95"),
                      shed_rate=st.get("shed_rate"))

    def overload(self) -> float:
        """Aggregate queue pressure over routable replicas (1.0 when none
        are routable — total overload by definition)."""
        depth = cap = 0
        for idx, h in enumerate(self.health):
            if not h.routable:
                continue
            g = self._gauges(idx)
            depth += int(g.get("queue_depth", 0))
            cap += int(g.get("queue_capacity", 0))
        if cap <= 0:
            return 1.0
        return depth / cap

    def _evaluate_ladder(self):
        c = self.config
        score = self.overload()
        n = len(c.ladder_enter)
        while self.tier < n and score >= c.ladder_enter[self.tier]:
            self._set_tier(self.tier + 1, score)
        if (self.tier > 0 and score <= c.ladder_exit[self.tier - 1]
                and self._step_count - self._tier_changed_step
                >= c.ladder_dwell_steps):
            self._set_tier(self.tier - 1, score)

    def _set_tier(self, tier: int, score: float):
        old, self.tier = self.tier, tier
        self._tier_changed_step = self._step_count
        self._counters["tier_transitions"] += 1
        self._emit("tier", from_tier=old, to_tier=tier,
                   score=round(score, 4))

    # ------------------------------------------------------------------
    # rolling restarts
    def start_drain(self, idx: int):
        """Stop routing new work to replica ``idx``; in-flight requests
        finish in place (a ``replica.drained`` event fires when the last
        one does)."""
        self.health[idx].start_drain()
        self._probe_req.pop(idx, None)

    def reactivate(self, idx: int, replica=None):
        """Bring a drained (or replaced) replica back into rotation —
        optionally swapping in a fresh engine object (the restarted
        process)."""
        if replica is not None:
            if self._assigned[idx]:
                # the old engine is being discarded with work still on
                # it: fail the work over BEFORE the swap (cancel must
                # reach the old engine) or drain() would poll orphaned
                # proxies forever
                self._failover_replica(idx, "reactivate")
            self.replicas[idx] = replica
        self.health[idx].reactivate()

    # ------------------------------------------------------------------
    @property
    def pending(self) -> bool:
        return bool(self.requests)

    def drain(self, max_steps: Optional[int] = None) -> List[RouterRequest]:
        out: List[RouterRequest] = []
        steps = 0
        while self.pending and (max_steps is None or steps < max_steps):
            out.extend(self.step())
            steps += 1
        return out

    def generate_batch(self, prompts, max_new_tokens: int = 0, **kwargs):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens, **kwargs)
                for p in prompts]
        self.drain()
        return [r.tokens if r.state == rq.FINISHED else None for r in reqs]

    def reset_stats(self):
        """Counter epoch boundary (bench warmup -> measured window); live
        requests and health state are untouched."""
        self.finished.clear()
        self._counters = {"submitted": 0, "finished": 0, "shed": 0,
                          "failovers": 0, "deduped_tokens": 0,
                          "replay_divergence": 0, "tier_transitions": 0,
                          "shed_reasons": {}}

    def stats(self) -> dict:
        s = self._counters
        total = max(1, s["submitted"])
        ttfts = [r.record()["ttft_ms"] for r in self.finished
                 if r.first_token_ts]
        return {
            "tier": self.tier,
            "replica_states": [h.state for h in self.health],
            "breaker_trips": sum(h.trips for h in self.health),
            "finished": s["finished"], "shed": s["shed"],
            "shed_reasons": dict(s["shed_reasons"]),
            "failovers": s["failovers"],
            "deduped_tokens": s["deduped_tokens"],
            "replay_divergence": s["replay_divergence"],
            "tier_transitions": s["tier_transitions"],
            "availability": round(s["finished"] / total, 4),
            "ttft_ms_p50": _pct(ttfts, 50),
            "ttft_ms_p95": _pct(ttfts, 95),
            "live": len(self.requests),
        }

    def destroy(self):
        for r in self.replicas:
            destroy = getattr(r, "destroy", None)
            if destroy is not None:
                destroy()
