"""One in-flight generation request.

Lifecycle: ``queued`` -> ``running`` (owns a decode slot + cache blocks)
-> ``finished`` (reason: ``eos`` | ``max_tokens`` | ``deadline``), or
``shed`` straight from submit/queue (reason: ``queue_full`` |
``inflight_tokens`` | ``too_long`` | ``deadline``). Timestamps are
host-monotonic; :meth:`Request.record` turns them into the telemetry
payload (TTFT, queue wait, tokens/s) the serving event stream carries.
"""

import dataclasses
import itertools
from typing import Any, Callable, List, Optional

QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
SHED = "shed"

_ids = itertools.count()


def _auto_id() -> str:
    return f"req-{next(_ids)}"


@dataclasses.dataclass
class Request:
    prompt: Any                       # 1-D int sequence (list/np array)
    max_new_tokens: int = 0           # 0 = serving default
    request_id: str = dataclasses.field(default_factory=_auto_id)
    eos_token_id: int = -1            # -1 disables early stop
    deadline_ms: float = 0.0          # 0 = serving default
    # stream(request, token, done) fires once per generated token, on the
    # scheduler thread, in generation order
    stream: Optional[Callable] = None
    # ---- keyed sampling (serving.sampling; engine resolves None knobs
    # to the block's defaults at admission and validates ranges) ----
    do_sample: bool = False
    # the request's reproducibility key: with do_sample on, token P is a
    # pure function of (seed, P, logits) — replayable state, carried
    # across failover/migration verbatim. None + do_sample = unseeded
    # legacy sampling, which a keyed engine sheds loudly.
    seed: Optional[int] = None
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None

    # ---- runtime state (owned by the scheduler/engine) ----
    state: str = QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None
    submit_ts: float = 0.0
    admit_ts: float = 0.0             # left the queue, won a decode slot
    first_token_ts: float = 0.0
    finish_ts: float = 0.0
    slot: int = -1
    length: int = 0                   # tokens currently in the KV cache
    # ---- prefix-cache / chunked-prefill accounting (set at admit by
    # the scheduler, advanced by the engine's prefill path) ----
    cached_len: int = 0               # prompt tokens already in the pool
    prefix_hit_tokens: int = 0        # matched cached prefix length
    blocks_shared: int = 0            # physical blocks mapped read-only
    prefill_chunks: int = 0           # chunk-program calls this prefill
    # (src, dst) pool blocks: dst must receive a device copy of src's
    # rows before any append (partial-tail copy-on-write), or None
    cow: Optional[tuple] = None
    # ---- speculative-decoding accounting (advanced by the engine's
    # verify step; zero when speculation is off or never proposed) ----
    draft_tokens: int = 0             # proposer tokens sent to verify
    accepted_tokens: int = 0          # drafts the target model agreed with
    # ---- span-tracing context (telemetry/tracing.py) ----
    # {"trace": id, "parent": span id, ...}: set by the serving engine at
    # submit (tracing enabled), or stamped by the multi-replica router so
    # replica-side spans join the CLIENT's trace under the current
    # attempt span (a failover continues one trace, not two). None when
    # tracing is off — every consumer guards on it.
    trace: Optional[dict] = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def keyed(self) -> bool:
        """Replayable sampled request: every emitted position's token is
        regenerable bit-exactly from (seed, position) by any replica."""
        return self.do_sample and self.seed is not None

    @property
    def positions_emitted(self) -> int:
        """Generated positions already streamed — with ``length`` and the
        token list, the ONLY sampler state there is (counter-based keys
        have no hidden rng to carry across a migration or replay)."""
        return len(self.tokens)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, SHED)

    def emit_token(self, token: int, done: bool):
        self.tokens.append(int(token))
        if self.stream is not None:
            self.stream(self, int(token), done)

    def record(self) -> dict:
        """JSON-safe per-request telemetry payload."""
        gen_secs = max(self.finish_ts - self.first_token_ts, 0.0)
        return {
            "request_id": self.request_id,
            "state": self.state,
            "reason": self.finish_reason,
            "do_sample": bool(self.do_sample),
            "prompt_len": self.prompt_len,
            "new_tokens": len(self.tokens),
            "queue_ms": round(1e3 * max(
                self.admit_ts - self.submit_ts, 0.0), 3)
            if self.admit_ts else None,
            "ttft_ms": round(1e3 * (self.first_token_ts - self.submit_ts), 3)
            if self.first_token_ts else None,
            "tokens_per_sec": round(len(self.tokens) / gen_secs, 2)
            if len(self.tokens) > 1 and gen_secs > 0 else None,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "blocks_shared": self.blocks_shared,
            "prefill_chunks": self.prefill_chunks,
            "draft_tokens": self.draft_tokens,
            "accepted_tokens": self.accepted_tokens,
            "acceptance_rate": round(
                self.accepted_tokens / self.draft_tokens, 4)
            if self.draft_tokens else None,
        }
