"""Tenant identity, quotas and SLO accounting for the serving gateway.

Pure host-side policy (jax-free, GL01): a :class:`TenantTable` resolves
an API key to a :class:`Tenant`, and each tenant carries its own
token buckets (requests/s and tokens/s), concurrent-inflight quota,
SLO class (priority + deadline defaults), deterministic trace-sampling
accumulator and sliding-window error budget. All timing reads the
injected clock (GL07 seam) — the trace-replay harness runs the whole
admission plane on simulated time.

Admission outcomes are strings the gateway maps to HTTP statuses::

    ""          admitted
    "rate"      request token bucket empty        -> 429 + Retry-After
    "tokens"    generation token bucket empty     -> 429 + Retry-After
    "inflight"  max_inflight concurrent requests  -> 429 + Retry-After
"""

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.serving.config import (GatewayConfig,
                                          GatewayTenantConfig,
                                          SloClassConfig)

ANONYMOUS = "anonymous"


class TokenBucket:
    """Classic token bucket on an injectable clock. ``rate <= 0`` means
    unlimited (every take succeeds, nothing is tracked). ``burst <= 0``
    sizes the bucket at one second of the rate, minimum 1."""

    def __init__(self, rate: float, burst: float = 0.0,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(self.rate, 1.0)
        self.clock = clock
        self.level = self.burst
        self._last = clock()

    def _refill(self, now: float):
        self.level = min(self.burst,
                         self.level + max(now - self._last, 0.0) * self.rate)
        self._last = now

    def ask(self, n: float = 1.0) -> float:
        """Refill, then return 0.0 when ``n`` tokens are available or the
        seconds until they would be. Does not deduct."""
        if self.rate <= 0:
            return 0.0
        self._refill(self.clock())
        if self.level >= n:
            return 0.0
        return (n - self.level) / self.rate

    def take(self, n: float = 1.0):
        if self.rate <= 0:
            return
        self.level -= n


class Tenant:
    """One tenant's live quota/SLO state. Thread-safe: the gateway's
    handler threads admit/release concurrently with the step loop
    recording outcomes."""

    def __init__(self, cfg: GatewayTenantConfig, slo: SloClassConfig,
                 clock=time.monotonic, budget_window: int = 256):
        self.name = cfg.name
        self.cfg = cfg
        self.slo = slo
        self.slo_class = cfg.slo_class
        self.priority = slo.priority
        self.deadline_ms = cfg.deadline_ms or slo.deadline_ms
        self.clock = clock
        self.req_bucket = TokenBucket(cfg.requests_per_sec,
                                      cfg.burst_requests, clock)
        self.tok_bucket = TokenBucket(cfg.tokens_per_sec,
                                      cfg.burst_tokens, clock)
        self.inflight = 0
        self._window: deque = deque(maxlen=int(budget_window))
        self._sample_acc = 0.0
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def admit(self, est_tokens: float = 0.0) -> Tuple[str, float]:
        """One admission attempt: ``("", 0.0)`` admits (quota charged,
        inflight incremented — pair with :meth:`release`), otherwise
        ``(reason, retry_after_secs)`` with nothing charged."""
        with self._lock:
            if (self.cfg.max_inflight
                    and self.inflight >= self.cfg.max_inflight):
                return "inflight", 0.0
            wait = self.req_bucket.ask(1.0)
            if wait > 0.0:
                return "rate", wait
            if est_tokens > 0.0:
                wait = self.tok_bucket.ask(float(est_tokens))
                if wait > 0.0:
                    return "tokens", wait
            self.req_bucket.take(1.0)
            if est_tokens > 0.0:
                self.tok_bucket.take(float(est_tokens))
            self.inflight += 1
            return "", 0.0

    def release(self):
        with self._lock:
            self.inflight = max(self.inflight - 1, 0)

    # ------------------------------------------------------------------
    def sample_trace(self) -> bool:
        """Deterministic rate-proportional sampling: an accumulator, not
        a PRNG, so replays are bit-reproducible."""
        rate = self.cfg.trace_sample_rate
        if rate <= 0.0:
            return False
        with self._lock:
            self._sample_acc += rate
            if self._sample_acc >= 1.0 - 1e-9:
                self._sample_acc -= 1.0
                return True
        return False

    # ------------------------------------------------------------------
    def record_outcome(self, shed: bool, ttft_ms: Optional[float] = None):
        """Burn the error budget: a request violates the SLO when it was
        shed, or when the class has a TTFT target and missed it."""
        bad = bool(shed)
        if not bad and self.slo.ttft_ms > 0 and ttft_ms is not None:
            bad = ttft_ms > self.slo.ttft_ms
        with self._lock:
            self._window.append(1 if bad else 0)

    def budget_remaining(self) -> float:
        """1.0 = untouched, 0.0 = spent: the bad fraction over the
        window, normalized by the class' allowed ``error_budget``."""
        with self._lock:
            if not self._window:
                return 1.0
            bad_frac = sum(self._window) / len(self._window)
        budget = self.slo.error_budget
        if budget <= 0.0:
            return 0.0 if bad_frac > 0 else 1.0
        return max(0.0, min(1.0, 1.0 - bad_frac / budget))


class TenantTable:
    """API key -> :class:`Tenant` resolution for one gateway. With no
    configured tenants the gateway is open: :meth:`resolve` maps ANY
    key (or none) to a quota-free anonymous tenant at ``best_effort``."""

    def __init__(self, config: GatewayConfig, clock=time.monotonic):
        self.config = config
        self.clock = clock
        self.tenants: List[Tenant] = []
        self._by_key: Dict[str, Tenant] = {}
        for row in config.tenants:
            tenant = Tenant(row, getattr(config, row.slo_class),
                            clock=clock, budget_window=config.budget_window)
            self.tenants.append(tenant)
            self._by_key[row.api_key] = tenant
        self._anonymous: Optional[Tenant] = None
        if not self.tenants:
            anon = GatewayTenantConfig(name=ANONYMOUS, api_key=ANONYMOUS)
            self._anonymous = Tenant(anon, config.best_effort, clock=clock,
                                     budget_window=config.budget_window)
            self.tenants.append(self._anonymous)

    @property
    def open(self) -> bool:
        return self._anonymous is not None

    def resolve(self, api_key: Optional[str]) -> Optional[Tenant]:
        if self._anonymous is not None:
            return self._anonymous
        if not api_key:
            return None
        return self._by_key.get(api_key)
