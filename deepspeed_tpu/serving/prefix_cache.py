"""Radix prefix cache over paged KV blocks.

A trie keyed on token-id *block chunks* (one edge = ``block_size``
token ids = one immutable, fully-written pool block). On admission the
scheduler asks :meth:`match` for the longest cached prefix of the new
prompt; the matched blocks are mapped read-only into the sequence's
block table (a refcount bump in :class:`BlockManager` — zero device
work) and prefill runs only on the unmatched tail. After a prompt's
prefill completes the engine calls :meth:`insert` so the next request
with the same system prompt hits.

Two sharing granularities:

- **full blocks** — an interior/leaf trie node per fully-written block.
  These are immutable by construction (paged writes only ever append at
  positions past the owner's prompt, i.e. into later blocks), so any
  number of sequences may read them concurrently.
- **one partial tail per node** — a prompt whose length is not a block
  multiple leaves its last block partially filled; that block is
  registered as a *tail* (token tuple -> block) under the node its full
  prefix reaches. A matching request may reuse those rows too, but only
  through **copy-on-write**: the block will be appended to, so
  :meth:`match` hands it back as ``cow_src`` and the engine copies it
  into the sequence's own fresh block before any write.

The match is capped at ``len(prompt) - 1`` tokens: at least one tail
token must run through the model to produce the first sampled logits.

Eviction is owned by the :class:`BlockManager`: cached blocks at
refcount zero sit on its LRU evictable ladder, and when an allocation
recycles one the manager's ``on_evict`` hook lands here —
:meth:`_drop_block` removes the trie entry and prunes the orphaned
subtree (a chain with a hole in it can never be matched again, so its
blocks go straight back to the free list).

Host-only by contract: no jax imports (AST import-hygiene pinned) —
matching is pure token-tuple dict walks, microseconds per admit.
"""

from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.serving.blocks import GARBAGE_BLOCK, BlockManager


class _Node:
    __slots__ = ("parent", "chunk", "block", "children", "tails")

    def __init__(self, parent: Optional["_Node"],
                 chunk: Optional[Tuple[int, ...]], block: Optional[int]):
        self.parent = parent
        self.chunk = chunk               # the edge from parent (token tuple)
        self.block = block               # physical pool block (None = root)
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.tails: Dict[Tuple[int, ...], int] = {}  # partial-block entries


class PrefixCache:
    """Longest-cached-prefix index for the serving admission path."""

    def __init__(self, blocks: BlockManager):
        self.blocks = blocks
        self.block_size = blocks.block_size
        self._root = _Node(None, None, None)
        # physical block -> its trie location, for O(1) eviction:
        # ("node", node) for full blocks, ("tail", node, tokens) for tails
        self._by_block: Dict[int, tuple] = {}
        self.stats = {"lookups": 0, "hits": 0, "hit_tokens": 0,
                      "inserted_blocks": 0, "evicted_blocks": 0}
        blocks.on_evict = self._drop_block

    def __len__(self) -> int:
        return len(self._by_block)

    # ------------------------------------------------------------------
    def match(self, prompt: Sequence[int]
              ) -> Tuple[List[int], Optional[int], int]:
        """Longest cached prefix of ``prompt``, capped at
        ``len(prompt) - 1`` tokens.

        Returns ``(shared_blocks, cow_src, matched_tokens)``:
        ``shared_blocks`` are full blocks to map read-only (in logical
        order), ``cow_src`` is an optional partial tail block whose
        first ``matched_tokens - len(shared_blocks) * block_size`` rows
        are valid and must be copied before use, and ``matched_tokens``
        is the total prefix length already present in the pool.
        """
        bs = self.block_size
        usable = len(prompt) - 1
        self.stats["lookups"] += 1
        node, shared, pos = self._root, [], 0
        while pos + bs <= usable:
            child = node.children.get(tuple(int(t) for t in
                                            prompt[pos:pos + bs]))
            if child is None:
                break
            shared.append(child.block)
            node = child
            pos += bs
        cow_src, tail_len = None, 0
        for toks, blk in node.tails.items():
            n = len(toks)
            if (n > tail_len and pos + n <= usable
                    and tuple(int(t) for t in prompt[pos:pos + n]) == toks):
                cow_src, tail_len = blk, n
        matched = pos + tail_len
        if matched:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += matched
            self.blocks.touch(shared + ([cow_src] if cow_src is not None
                                        else []))
        return shared, cow_src, matched

    # ------------------------------------------------------------------
    def insert(self, prompt: Sequence[int], table) -> int:
        """Index a just-prefilled prompt's blocks; returns how many new
        blocks were registered. Chunks already cached keep their
        existing physical block (the sequence's own duplicate stays a
        plain private block); the partial last block, if any, registers
        as a COW tail."""
        bs = self.block_size
        node, pos, i, added = self._root, 0, 0, 0
        while pos + bs <= len(prompt):
            chunk = tuple(int(t) for t in prompt[pos:pos + bs])
            child = node.children.get(chunk)
            if child is None:
                blk = int(table[i])
                if blk == GARBAGE_BLOCK or blk in self._by_block:
                    # a table should never pad inside the prompt span and
                    # one physical block indexes at most one trie entry;
                    # either way there is nothing safe to register past
                    # this point
                    return added
                child = _Node(node, chunk, blk)
                node.children[chunk] = child
                self._by_block[blk] = ("node", child)
                self.blocks.mark_cached(blk)
                added += 1
            node = child
            pos += bs
            i += 1
        tail = tuple(int(t) for t in prompt[pos:])
        if 0 < len(tail) < bs and tail not in node.tails:
            blk = int(table[i])
            if blk != GARBAGE_BLOCK and blk not in self._by_block:
                node.tails[tail] = blk
                self._by_block[blk] = ("tail", node, tail)
                self.blocks.mark_cached(blk)
                added += 1
        self.stats["inserted_blocks"] += added
        return added

    # ------------------------------------------------------------------
    def _drop_block(self, block: int):
        """BlockManager recycled a cached block (LRU eviction): remove
        its trie entry, and prune the orphaned subtree — a descendant
        chain with a missing link can never be matched, so its blocks'
        storage returns to the free list immediately."""
        entry = self._by_block.pop(int(block), None)
        self.stats["evicted_blocks"] += 1
        if entry is None:
            return
        if entry[0] == "tail":
            _, node, toks = entry
            node.tails.pop(toks, None)
            return
        node = entry[1]
        if node.parent is not None:
            node.parent.children.pop(node.chunk, None)
        self._prune(node)

    def _prune(self, node: _Node):
        """Drop a detached subtree's cache registrations (the evicted
        root's own block is already recycled by the manager)."""
        stack = list(node.children.values())
        for toks, blk in node.tails.items():
            self._release_entry(blk)
        node.tails.clear()
        node.children.clear()
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self._release_entry(n.block)
            for blk in n.tails.values():
                self._release_entry(blk)
            n.children.clear()
            n.tails.clear()

    def _release_entry(self, block: int):
        if self._by_block.pop(int(block), None) is not None:
            self.blocks.drop_cached(int(block))
