"""Serving configuration (the ``serving`` block of the inference config).

With the block absent the serving layer does not exist: the inference
engines' compiled HLO is byte-identical (pinned in
``tests/unit/test_serving.py``) and ``generate()`` keys its compile
cache exactly as before. With it present, ``ServingEngine`` serves
continuous-batching traffic and the legacy ``generate()`` pads prompt
lengths up to the bucket set before keying its compile cache.

This module must stay import-light (no jax, no inference imports): the
inference config parses it lazily, and the pure-Python scheduler tests
run without touching a device.
"""

import math
from typing import List, Optional

from pydantic import field_validator, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

SHED = "shed"
QUEUE = "queue"

PROMPT_LOOKUP = "prompt_lookup"
DRAFT_MODEL = "draft_model"

# SLO classes the gateway maps onto the scheduler's priority floor
SLO_GOLD = "gold"
SLO_BEST_EFFORT = "best_effort"
SLO_BATCH = "batch"
SLO_CLASSES = (SLO_GOLD, SLO_BEST_EFFORT, SLO_BATCH)


class SpeculativeConfig(DeepSpeedConfigModel):
    """The ``serving.speculative`` block: draft-and-verify decoding on
    the fixed-slot decode loop. Absent (the default) speculation does
    not exist — the decode program and its compiled HLO are
    byte-identical to previous releases. Present, each decode step
    proposes up to ``num_speculative_tokens`` continuation tokens per
    slot on the host and ONE compiled verify program scores them all in
    a single dispatch; the longest prefix the target model agrees with
    is committed (1 to k+1 tokens per step for one dispatch). Greedy
    decode is the exact accept oracle, so the emitted stream is
    bit-identical to non-speculative decode — ``serving.do_sample``
    must stay off while speculation is on."""

    enabled: bool = True
    # "prompt_lookup": n-gram match against the request's own context
    # (zero extra model); "draft_model": a small injected draft
    # (ServingEngine(..., draft_model=...)) guesses greedily
    proposer: str = PROMPT_LOOKUP
    # k — draft tokens proposed (and query rows verified) per step; a
    # config constant, so the verify program's shape is static and the
    # zero-steady-state-retrace pin holds (short proposals right-pad
    # against the garbage block)
    num_speculative_tokens: int = 4
    # prompt-lookup knobs: suffix n-gram sizes tried, longest first
    prompt_lookup_min_ngram: int = 1
    prompt_lookup_max_ngram: int = 3
    # trailing context tokens the n-gram scan searches (0 = unbounded).
    # The scan is host Python on the step-critical path: a miss costs
    # the FULL scan every step, so long-context serving needs the bound
    prompt_lookup_window: int = 1024
    # draft-model knob: trailing context tokens the draft sees per step
    # (0 = the full prompt + generation; the draft runs every step, so
    # this bounds its per-step cost)
    draft_context_window: int = 0

    @field_validator("num_speculative_tokens")
    @classmethod
    def _k(cls, v):
        if v <= 0:
            raise ValueError(
                "serving.speculative.num_speculative_tokens must be > 0 "
                f"(k proposed tokens per verify step), got {v}")
        return v

    @field_validator("proposer")
    @classmethod
    def _proposer(cls, v):
        if v not in (PROMPT_LOOKUP, DRAFT_MODEL):
            raise ValueError(
                f"serving.speculative.proposer must be '{PROMPT_LOOKUP}' "
                f"or '{DRAFT_MODEL}', got {v!r}")
        return v

    @field_validator("draft_context_window", "prompt_lookup_window")
    @classmethod
    def _window(cls, v, info):
        if v < 0:
            raise ValueError(
                f"serving.speculative.{info.field_name} must be >= 0 "
                f"(0 = full context), got {v}")
        return v

    @model_validator(mode="after")
    def _ngrams(self):
        if not (1 <= self.prompt_lookup_min_ngram
                <= self.prompt_lookup_max_ngram):
            raise ValueError(
                "serving.speculative needs 1 <= prompt_lookup_min_ngram "
                f"<= prompt_lookup_max_ngram, got min="
                f"{self.prompt_lookup_min_ngram} max="
                f"{self.prompt_lookup_max_ngram}")
        return self


class SamplingConfig(DeepSpeedConfigModel):
    """The ``serving.sampling`` block: reproducible keyed sampling on
    the fixed-slot decode loop. Absent (the default) keyed sampling
    does not exist — the compiled prefill/decode/chunk programs are
    byte-identical to previous releases (the standard zero-overhead
    pin). Present, a request submitted with ``do_sample=True`` and a
    ``seed`` samples through a counter-based threefry key folded from
    ``(seed, absolute position)`` INSIDE the compiled program, with
    temperature/top-k/top-p traced per slot: the emitted token is a
    pure function of (seed, position, logits), independent of slot
    index, batch composition, and tp layout — so failover replay,
    live migration, and trace replay are all bit-exact for sampled
    streams, exactly as they are for greedy ones.

    One sampling authority per engine: ``serving.do_sample`` (the
    legacy engine-level sampler, shared-rng and NOT replayable) must
    stay off, and speculative decoding (whose accept oracle is the
    greedy stream) cannot be combined with this block."""

    enabled: bool = True
    # per-request defaults a sampled request inherits when it leaves
    # temperature/top_k/top_p unset (seed has no default on purpose:
    # an unseeded do_sample request is not replayable and sheds loudly)
    default_temperature: float = 1.0
    default_top_k: int = 0
    default_top_p: float = 0.0

    @field_validator("default_temperature")
    @classmethod
    def _temp(cls, v):
        if v <= 0:
            raise ValueError(
                "serving.sampling.default_temperature must be > 0, "
                f"got {v}")
        return v

    @field_validator("default_top_k")
    @classmethod
    def _topk(cls, v):
        if v < 0:
            raise ValueError(
                "serving.sampling.default_top_k must be >= 0 "
                f"(0 = disabled), got {v}")
        return v

    @field_validator("default_top_p")
    @classmethod
    def _topp(cls, v):
        if not 0.0 <= v <= 1.0:
            raise ValueError(
                "serving.sampling.default_top_p must be in [0, 1] "
                f"(0 = disabled), got {v}")
        return v


class ReplayConfig(DeepSpeedConfigModel):
    """The ``serving.replay`` block: workload-replay defaults consumed by
    :class:`deepspeed_tpu.serving.replay.TraceReplayer` (the trace-driven
    load harness). Pure bookkeeping — the block never touches the serving
    engines or their compiled programs; it only parameterizes how a
    recorded arrival trace is replayed against them."""

    enabled: bool = True
    # JSONL arrival trace to replay ("" = the caller passes records)
    trace_path: str = ""
    # simulated seconds each replay iteration advances the fake clock by
    # (one target.step() per iteration — smaller = finer arrival timing,
    # more steps per simulated second)
    step_secs: float = 0.05
    # deterministic prompt-token synthesis seed (same seed + same trace
    # = bit-identical prompts, the replay-determinism contract)
    seed: int = 0
    # synthesized prompt tokens are drawn from [1, vocab_size)
    vocab_size: int = 1000
    # hard iteration bound (0 = run to trace end + drain) — the guard
    # against a wedged target spinning the replay loop forever
    max_steps: int = 0

    @field_validator("step_secs")
    @classmethod
    def _step(cls, v):
        if v <= 0:
            raise ValueError(
                f"serving.replay.step_secs must be > 0 (simulated seconds "
                f"per replay iteration), got {v}")
        return v

    @field_validator("vocab_size")
    @classmethod
    def _vocab(cls, v):
        if v < 2:
            raise ValueError(
                f"serving.replay.vocab_size must be >= 2, got {v}")
        return v


class FleetConfig(DeepSpeedConfigModel):
    """The ``serving.fleet`` block: the SLO error-budget autoscaler over
    the multi-replica router (:class:`deepspeed_tpu.serving.router.
    FleetManager`). Absent (the default) the fleet layer does not exist
    — the router runs its static replica set and the compiled programs
    are byte-identical. Present (requires ``serving.router``), scaling
    decisions walk replicas through the router's ``start_drain`` /
    ``reactivate`` seams against error budgets: scale-down drains and
    parks engines, scale-up reactivates parked replicas (warm — their
    compiled programs are live) or builds fresh ones through the
    ``ReplicaFactory`` seam."""

    enabled: bool = True
    # fleet size bounds (active = HEALTHY + DEGRADED replicas)
    min_replicas: int = 1
    max_replicas: int = 4
    # ---- SLO error budgets (0 = that budget is off) ----
    # TTFT p95 target: at most 5% of finished requests may exceed it (the
    # p95 semantic IS the budget); burn rate = observed-over fraction/0.05
    target_ttft_p95_ms: float = 0.0
    # allowed shed fraction; burn rate = observed shed rate / this
    target_shed_rate: float = 0.0
    # ---- burn-rate windows (router steps) ----
    fast_window_steps: int = 8     # urgent scale-up detection
    slow_window_steps: int = 64    # budget-remaining accounting + quiet gate
    # fast-window burn rate at or above this triggers scale-up (1.0 =
    # burning exactly the budget; >1 tolerates short spikes)
    burn_rate_fast: float = 1.0
    # ---- load thresholds (router overload score, 0..1) ----
    scale_up_load: float = 0.8     # queue pressure alone can trigger growth
    scale_down_load: float = 0.3   # pressure must sit below this to shrink
    # ---- hysteresis + cooldowns (router steps) ----
    scale_up_cooldown_steps: int = 4
    scale_down_cooldown_steps: int = 16
    # consecutive quiet steps (low load AND fast burns within budget)
    # required before a scale-down — the anti-flap guard
    scale_down_quiet_steps: int = 16
    # ---- the ReplicaFactory seam ----
    # steps to wait after a failed factory build before retrying; doubles
    # per consecutive failure (the retry_io exponential series)
    factory_backoff_steps: int = 4
    # a drain older than this many steps force-yields its in-flight work
    # to survivors and parks anyway (0 = wait forever) — scale-down must
    # never deadlock drain() behind one wedged replica
    drain_timeout_steps: int = 0
    # ---- migrate-based defragmentation (needs serving.migration) ----
    # pool-fragmentation gauge (1 - committed/allocated-capacity) at or
    # above this triggers a migrate-based rebalance of the worst replica
    # (0 = rebalance off)
    rebalance_fragmentation: float = 0.0
    # steps between rebalance sweeps — defrag must not thrash the pools
    rebalance_cooldown_steps: int = 16
    # in-flight requests moved off the fragmented replica per sweep
    rebalance_max_requests: int = 1

    @field_validator("min_replicas", "max_replicas", "fast_window_steps",
                     "slow_window_steps", "scale_up_cooldown_steps",
                     "scale_down_cooldown_steps", "scale_down_quiet_steps",
                     "factory_backoff_steps", "rebalance_cooldown_steps",
                     "rebalance_max_requests")
    @classmethod
    def _positive(cls, v, info):
        if v <= 0:
            raise ValueError(
                f"serving.fleet.{info.field_name} must be > 0, got {v}")
        return v

    @field_validator("target_ttft_p95_ms", "target_shed_rate",
                     "burn_rate_fast", "drain_timeout_steps")
    @classmethod
    def _non_negative(cls, v, info):
        if v < 0:
            raise ValueError(
                f"serving.fleet.{info.field_name} must be >= 0, got {v}")
        return v

    @field_validator("rebalance_fragmentation")
    @classmethod
    def _frag(cls, v):
        if not (0.0 <= v <= 1.0):
            raise ValueError(
                "serving.fleet.rebalance_fragmentation must be in [0, 1] "
                f"(0 = rebalance off), got {v}")
        return v

    @model_validator(mode="after")
    def _bounds(self):
        if self.min_replicas > self.max_replicas:
            raise ValueError(
                "serving.fleet needs min_replicas <= max_replicas, got "
                f"{self.min_replicas} > {self.max_replicas}")
        if not (0.0 <= self.scale_down_load < self.scale_up_load <= 1.0):
            raise ValueError(
                "serving.fleet needs 0 <= scale_down_load < scale_up_load "
                f"<= 1 (load hysteresis), got down={self.scale_down_load} "
                f"up={self.scale_up_load}")
        return self


class MigrationConfig(DeepSpeedConfigModel):
    """The ``serving.migration`` block: live KV-block migration — move a
    running sequence's committed pool blocks (plus int8 side pools and
    scales, riding the same block indices) to a peer replica and splice
    the request into a free decode slot there, mid-stream, with no
    prefill dispatch. Absent (the default) the migration layer does not
    exist: failover replays, drains wait in place, and the compiled
    decode HLO is byte-identical (the standard zero-overhead pin).
    Present, three consumers use the one primitive: router failover on a
    breaker trip or host-observed stall whose source pool is still
    readable (a hard crash keeps the deterministic-replay path),
    fleet-manager drain/scale-down (``drain_timeout_steps`` becomes the
    fallback, not the plan), and autoscaler-triggered defragmentation of
    the most fragmented replica."""

    enabled: bool = True
    # migrate-first on breaker trip / stall failover (source pool still
    # readable); off = PR 6 deterministic replay exactly as before
    failover: bool = True
    # fleet drains move in-flight work to survivors instead of waiting
    drain: bool = True
    # autoscaler-triggered migrate-based rebalance of fragmented pools
    rebalance: bool = True
    # cap on requests moved per drain/rebalance sweep (0 = all of them)
    max_requests_per_sweep: int = 0

    @field_validator("max_requests_per_sweep")
    @classmethod
    def _sweep(cls, v):
        if v < 0:
            raise ValueError(
                "serving.migration.max_requests_per_sweep must be >= 0 "
                f"(0 = move everything), got {v}")
        return v


class RouterConfig(DeepSpeedConfigModel):
    """The ``serving.router`` block: N replica serving engines behind one
    submit()/drain() front door (:class:`deepspeed_tpu.serving.router.
    ReplicaRouter`). Absent (the default) the router layer does not
    exist — ``init_serving`` returns the plain single-engine
    ``ServingEngine`` and nothing about its behavior or compiled
    programs changes."""

    enabled: bool = True
    # replica engines init_serving builds when given a model (ignored
    # when the caller passes pre-built replicas)
    replicas: int = 2
    # ---- per-replica health state machine / circuit breaker ----
    # consecutive submit/step failures before the breaker trips
    failure_threshold: int = 3
    # half-open probe delay after a trip; doubles per trip (the same
    # exponential series resilience.integrity.retry_io walks)
    probe_backoff_secs: float = 0.5
    # breaker trips before the replica is declared DEAD
    max_trips: int = 4
    # host-observed step wall time above this is a stall verdict (the
    # hang-watchdog signal at router granularity); 0 = off
    stall_timeout_secs: float = 0.0
    # soft DEGRADED signals from the replica's own telemetry aggregates
    # (TTFT p95 / shed rate over the bounded records window); 0 = off
    degraded_ttft_ms: float = 0.0
    degraded_shed_rate: float = 0.0
    # hysteresis: DEGRADED recovers only below enter * exit_fraction
    degraded_exit_fraction: float = 0.5
    # ---- failover ----
    # resubmissions per request before it is failed as replica_lost
    max_failovers: int = 2
    # ---- SLO-guarded degradation ladder ----
    # overload score (aggregate queue depth / aggregate queue capacity
    # over routable replicas; 1.0 when none are routable) thresholds:
    # crossing enter[t] raises the tier to t+1 immediately, dropping back
    # below exit[t] lowers it one tier AFTER ladder_dwell_steps (the
    # hysteresis guard against tier flapping / timeout storms)
    ladder_enter: List[float] = [0.75, 0.9, 1.0]
    ladder_exit: List[float] = [0.5, 0.65, 0.8]
    ladder_dwell_steps: int = 8
    # tier 1+: clamp per-request max_new_tokens to this budget
    clamp_max_new_tokens: int = 16
    # tier 2+: shed submits whose priority is below this floor
    shed_priority_floor: int = 1

    @field_validator("replicas", "failure_threshold", "max_trips",
                     "max_failovers", "ladder_dwell_steps",
                     "clamp_max_new_tokens")
    @classmethod
    def _positive(cls, v, info):
        if v <= 0:
            raise ValueError(
                f"serving.router.{info.field_name} must be > 0, got {v}")
        return v

    @model_validator(mode="after")
    def _ladder(self):
        if len(self.ladder_enter) != len(self.ladder_exit):
            raise ValueError(
                "serving.router.ladder_enter and ladder_exit must have the "
                f"same length, got {self.ladder_enter} vs {self.ladder_exit}")
        for i, (en, ex) in enumerate(zip(self.ladder_enter,
                                         self.ladder_exit)):
            if ex >= en:
                raise ValueError(
                    "serving.router ladder hysteresis needs exit < enter "
                    f"at every tier, got exit[{i}]={ex} >= enter[{i}]={en}")
        if sorted(self.ladder_enter) != list(self.ladder_enter):
            raise ValueError("serving.router.ladder_enter must be "
                             f"non-decreasing, got {self.ladder_enter}")
        return self


class SloClassConfig(DeepSpeedConfigModel):
    """One SLO class (``serving.gateway.gold`` / ``best_effort`` /
    ``batch``): the knobs a tenant inherits from its class. ``priority``
    feeds the scheduler/router priority floor (the PR 6 degradation
    ladder sheds submits below ``serving.router.shed_priority_floor``),
    ``deadline_ms`` is the class default per-request deadline, and
    ``ttft_ms``/``error_budget`` define the class' error budget: a
    finished request burns budget when it was shed or its TTFT exceeded
    ``ttft_ms`` (0 = shed-only budget)."""

    # scheduler/router priority this class submits at
    priority: int = 0
    # class-default per-request deadline; 0 = engine default
    deadline_ms: float = 0.0
    # TTFT target the error budget counts against; 0 = shed-only
    ttft_ms: float = 0.0
    # fraction of recent requests allowed to violate the SLO
    error_budget: float = 0.05

    @field_validator("priority")
    @classmethod
    def _priority(cls, v):
        if v < 0:
            raise ValueError(
                f"serving.gateway SLO class priority must be >= 0, got {v}")
        return v

    @field_validator("deadline_ms", "ttft_ms", "error_budget")
    @classmethod
    def _nonneg(cls, v, info):
        if v < 0:
            raise ValueError(
                f"serving.gateway SLO class {info.field_name} must be "
                f">= 0, got {v}")
        return v


class GatewayTenantConfig(DeepSpeedConfigModel):
    """One row of ``serving.gateway.tenants``: an API-key identity plus
    its quotas. Rates of 0 mean unlimited; ``burst_*`` of 0 sizes the
    token bucket at one second of the rate (minimum 1)."""

    # tenant identity (the metrics/traces label)
    name: str = ""
    # the shared secret clients present (Authorization: Bearer <key>
    # or X-API-Key header)
    api_key: str = ""
    # SLO class: "gold" | "best_effort" | "batch"
    slo_class: str = SLO_BEST_EFFORT
    # token-bucket rate limits (0 = unlimited)
    requests_per_sec: float = 0.0
    tokens_per_sec: float = 0.0
    # bucket depths; 0 = one second of the rate (minimum 1)
    burst_requests: float = 0.0
    burst_tokens: float = 0.0
    # concurrent admitted-but-unfinished requests (0 = unlimited)
    max_inflight: int = 0
    # per-tenant deadline override; 0 = the SLO class default
    deadline_ms: float = 0.0
    # fraction of this tenant's requests that get a full request trace
    # with a `gateway` root span (0 = never, 1 = every request)
    trace_sample_rate: float = 0.0

    @field_validator("name", "api_key")
    @classmethod
    def _required(cls, v, info):
        if not v:
            raise ValueError(
                f"serving.gateway.tenants[].{info.field_name} is required")
        return v

    @field_validator("slo_class")
    @classmethod
    def _slo(cls, v):
        if v not in SLO_CLASSES:
            raise ValueError(
                "serving.gateway.tenants[].slo_class must be one of "
                f"{SLO_CLASSES}, got {v!r}")
        return v

    @field_validator("requests_per_sec", "tokens_per_sec",
                     "burst_requests", "burst_tokens", "deadline_ms")
    @classmethod
    def _nonneg(cls, v, info):
        if v < 0:
            raise ValueError(
                f"serving.gateway.tenants[].{info.field_name} must be "
                f">= 0, got {v}")
        return v

    @field_validator("max_inflight")
    @classmethod
    def _inflight(cls, v):
        if v < 0:
            raise ValueError(
                "serving.gateway.tenants[].max_inflight must be >= 0 "
                f"(0 = unlimited), got {v}")
        return v

    @field_validator("trace_sample_rate")
    @classmethod
    def _sample(cls, v):
        if not 0.0 <= v <= 1.0:
            raise ValueError(
                "serving.gateway.tenants[].trace_sample_rate must be in "
                f"[0, 1], got {v}")
        return v


class GatewayConfig(DeepSpeedConfigModel):
    """The ``serving.gateway`` block: the HTTP/SSE front door
    (:class:`deepspeed_tpu.serving.gateway.ServingGateway`). Absent (the
    default) the gateway does not exist — requests enter via Python
    ``submit()`` calls and the compiled programs are byte-identical (the
    standard zero-overhead pin; the gateway is pure host code and never
    imports jax, GL01-gated). With no ``tenants`` rows the gateway is
    open: requests need no API key and run as the anonymous tenant at
    the ``best_effort`` class with no quotas."""

    enabled: bool = True
    # bind address; port 0 = ephemeral (read it back from .port)
    host: str = "127.0.0.1"
    port: int = 0
    # request hardening: bodies above this are refused with 413
    max_body_bytes: int = 1048576
    # per-connection bounded SSE send queue (tokens); a slow reader that
    # overflows it sheds THAT request only, never the step loop
    send_queue_tokens: int = 256
    # Retry-After seconds attached to 429/503 responses (rate sheds use
    # the bucket's own refill estimate when it is larger)
    retry_after_secs: float = 1.0
    # backend overload score (router/fleet ``overload()``) at or above
    # which new submits get 503 before touching the queue; 0 = off
    overload_reject_threshold: float = 0.0
    # recent finished requests per tenant the error budget is burned
    # over (a bounded sliding window)
    budget_window: int = 256
    # handler wait granularity for terminal-state polls and the pump
    poll_secs: float = 0.05
    # own the step loop: a daemon thread drives ``gateway.step()`` while
    # work is pending (off = the caller drives steps, e.g. trace replay)
    pump: bool = False
    # ---- SLO classes ----
    gold: SloClassConfig = SloClassConfig(priority=2)
    best_effort: SloClassConfig = SloClassConfig(priority=1)
    batch: SloClassConfig = SloClassConfig(priority=0)
    # ---- tenant table (empty = open gateway, anonymous tenant) ----
    tenants: List[GatewayTenantConfig] = []

    @field_validator("port")
    @classmethod
    def _port(cls, v):
        if not 0 <= v <= 65535:
            raise ValueError(
                f"serving.gateway.port must be in [0, 65535], got {v}")
        return v

    @field_validator("max_body_bytes", "send_queue_tokens",
                     "budget_window")
    @classmethod
    def _positive(cls, v, info):
        if v <= 0:
            raise ValueError(
                f"serving.gateway.{info.field_name} must be > 0, got {v}")
        return v

    @field_validator("retry_after_secs", "overload_reject_threshold")
    @classmethod
    def _nonneg(cls, v, info):
        if v < 0:
            raise ValueError(
                f"serving.gateway.{info.field_name} must be >= 0, got {v}")
        return v

    @field_validator("poll_secs")
    @classmethod
    def _poll(cls, v):
        if v <= 0:
            raise ValueError(
                f"serving.gateway.poll_secs must be > 0, got {v}")
        return v

    @model_validator(mode="after")
    def _unique_tenants(self):
        names = [t.name for t in self.tenants]
        keys = [t.api_key for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(
                f"serving.gateway.tenants names must be unique, got {names}")
        if len(set(keys)) != len(keys):
            raise ValueError(
                "serving.gateway.tenants api_keys must be unique (two "
                "tenants sharing a key would be one identity)")
        return self


class ServingConfig(DeepSpeedConfigModel):
    enabled: bool = True
    # ---- paged KV cache ----
    # tokens per cache block; per-layer pools are [num_blocks, block_size,
    # H, D] and block 0 is the reserved garbage sink
    block_size: int = 16
    # total pool blocks; 0 = garbage block + decode_slots full-length
    # sequences (the conservative no-overcommit sizing)
    num_blocks: int = 0
    # longest prompt+generation the runtime admits; 0 = the model window
    max_model_len: int = 0
    # ---- continuous batching ----
    # concurrent decode sequences (the decode program's static batch)
    decode_slots: int = 4
    # prompt-length buckets for prefill (and the legacy generate() compile
    # cache); [] = powers of two from block_size up to max_model_len
    prompt_buckets: List[int] = []
    # ---- serving fast path (each key absent/zero = feature does not
    # exist and nothing about the compiled programs changes) ----
    # radix prefix cache: admissions match the longest cached prompt
    # prefix, map its blocks read-only (copy-on-write for a partial last
    # block) and prefill only the tail; released blocks park on an LRU
    # evictable ladder instead of freeing
    prefix_cache: bool = False
    # chunked prefill: prompts prefill in fixed chunks of this many
    # tokens, interleaved into the decode loop under the same per-step
    # token budget — long prompts stop monopolizing the program and the
    # power-of-two bucket ladder collapses to ONE chunk program. 0 = off
    # (whole-prompt bucketed prefill, exactly as before)
    prefill_chunk_tokens: int = 0
    # paged KV block dtype: "" = the model compute dtype; "int8"
    # quantizes K/V per block row (one scale per token x head, riding a
    # side pool indexed by the same block table) for 2-4x more concurrent
    # sequences per HBM byte
    kv_cache_dtype: str = ""
    # satellite: pad legacy generate() prompts up to the bucket set before
    # keying its compile cache (identical tokens via the left-padded mask
    # path; one compiled program per bucket instead of per prompt length)
    bucket_legacy_generate: bool = True
    # ---- admission control / backpressure ----
    max_queue_depth: int = 64
    # cap on committed tokens (prompt + max_new over queued + running);
    # 0 = unbounded
    max_inflight_tokens: int = 0
    # "shed": reject a submit that would exceed max_inflight_tokens;
    # "queue": accept it (queue depth still bounds) and defer slot
    # admission until running work drains below the cap
    shed_policy: str = SHED
    # default per-request deadline (submit -> finish), 0 = none; requests
    # past it are shed from the queue or abandoned mid-decode
    deadline_ms: float = 0.0
    default_max_new_tokens: int = 64
    # ---- sampling (engine-level; greedy default is the batch-invariance
    # contract: tokens bit-match per-request generate()) ----
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    # ---- reproducible keyed sampling (None = keyed sampling does not
    # exist; the compiled programs are byte-identical and a do_sample
    # request sheds `sampling_unsupported`) ----
    sampling: Optional[SamplingConfig] = None
    # ---- speculative decoding (None = speculation does not exist; the
    # decode program and its compiled HLO are byte-identical) ----
    speculative: Optional[SpeculativeConfig] = None
    # ---- multi-replica front door (None = the router layer does not
    # exist; single-engine serving is exactly as before) ----
    router: Optional[RouterConfig] = None
    # ---- fleet manager (None = no autoscaler; the router's replica set
    # is static exactly as before). Requires a router block. ----
    fleet: Optional[FleetConfig] = None
    # ---- workload-replay defaults (None = no defaults; the replay
    # harness takes explicit arguments). Never touches the engines. ----
    replay: Optional[ReplayConfig] = None
    # ---- live KV-block migration (None = migration does not exist:
    # failover replays, drains wait, compiled HLO byte-identical) ----
    migration: Optional[MigrationConfig] = None
    # ---- HTTP/SSE front door (None = the gateway does not exist;
    # requests enter via Python submit() exactly as before) ----
    gateway: Optional[GatewayConfig] = None

    @field_validator("block_size", "decode_slots")
    @classmethod
    def _positive(cls, v, info):
        if v <= 0:
            raise ValueError(f"serving.{info.field_name} must be > 0, "
                             f"got {v}")
        return v

    @field_validator("shed_policy")
    @classmethod
    def _policy(cls, v):
        if v not in (SHED, QUEUE):
            raise ValueError(
                f"serving.shed_policy must be '{SHED}' or '{QUEUE}', "
                f"got {v!r}")
        return v

    @field_validator("prompt_buckets")
    @classmethod
    def _buckets(cls, v):
        if any(b <= 0 for b in v):
            raise ValueError(f"serving.prompt_buckets must be positive, "
                             f"got {v}")
        return sorted(set(int(b) for b in v))

    @field_validator("prefill_chunk_tokens")
    @classmethod
    def _chunk(cls, v):
        if v < 0:
            raise ValueError(
                f"serving.prefill_chunk_tokens must be >= 0 (0 = whole-"
                f"prompt bucketed prefill), got {v}")
        return v

    @field_validator("kv_cache_dtype")
    @classmethod
    def _kv_dtype(cls, v):
        if v not in ("", "int8"):
            raise ValueError(
                f"serving.kv_cache_dtype must be '' (model dtype) or "
                f"'int8', got {v!r}")
        return v

    @model_validator(mode="after")
    def _fleet_needs_router(self):
        if (self.fleet is not None and self.fleet.enabled
                and (self.router is None or not self.router.enabled)):
            # the fleet manager scales the ROUTER's replica set through
            # its drain/reactivate seams — without a router there is
            # nothing to scale, and silently ignoring the block would
            # read as "autoscaling is on" when it is not
            raise ValueError(
                "serving.fleet requires a serving.router block (the fleet "
                "manager scales the router's replica set; add \"router\": "
                "{...} or drop the fleet block)")
        return self

    @model_validator(mode="after")
    def _speculative_needs_greedy(self):
        if (self.speculative is not None and self.speculative.enabled
                and self.do_sample):
            # the accept oracle is exact token equality against the
            # target's own greedy stream; a sampled stream has no such
            # oracle, so verification would silently change outputs
            raise ValueError(
                "serving.speculative requires greedy decoding "
                "(do_sample: false): draft acceptance is verified "
                "against the bit-reproducible greedy token stream")
        return self

    @model_validator(mode="after")
    def _sampling_one_authority(self):
        if self.sampling is not None and self.sampling.enabled:
            if self.do_sample:
                # the legacy engine-level sampler draws from ONE shared
                # rng stream — its tokens depend on dispatch order and
                # are unreplayable by construction; running both would
                # leave "which sampler owns this slot" ambiguous
                raise ValueError(
                    "serving.sampling requires do_sample: false — the "
                    "keyed sampler is per-REQUEST (submit with "
                    "do_sample=True and a seed); the engine-level "
                    "do_sample knob is the legacy shared-rng sampler")
            if self.speculative is not None and self.speculative.enabled:
                # the verify oracle is exact equality against the greedy
                # stream; rejection-sampling speculation over keyed
                # draws is the ROADMAP follow-up, not this block
                raise ValueError(
                    "serving.sampling cannot be combined with "
                    "serving.speculative: draft acceptance is verified "
                    "against the greedy token stream (rejection-sampled "
                    "speculation is not implemented)")
        return self


def resolve_buckets(buckets, max_len: int, floor: int = 8):
    """The prompt-length bucket set: the configured list (clipped to
    ``max_len``), or powers of two from ``floor`` up, always ending at
    ``max_len`` so every admissible prompt has a bucket. A small FIXED
    set is the whole point: every jitted shape comes from it, so
    steady-state retrace count is provably zero."""
    max_len = int(max_len)
    if buckets:
        out = sorted(set(int(b) for b in buckets if int(b) <= max_len))
    else:
        out = []
        b = max(1, int(floor))
        while b < max_len:
            out.append(b)
            b *= 2
    if not out or out[-1] != max_len:
        out.append(max_len)
    return out


def bucket_for(n: int, buckets):
    """Smallest bucket >= n, or None when n exceeds them all."""
    for b in buckets:
        if n <= b:
            return b
    return None


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    return max(1, math.ceil(n_tokens / block_size))
