"""deepspeed_tpu.runtime.resilience — the fault-tolerance layer.

ZeRO training that survives a real TPU pod: verified-good checkpoints
with a fallback chain (``integrity``), NaN/loss-spike policy enforcement
beyond the fp16 path (``sentinel``), stalled-collective detection with
dump-and-abort (``watchdog``), and the deterministic fault injectors the
test suite proves every degradation path with (``chaos``).

Off by default; enable via the ``resilience`` config block
(``runtime/config.py``)::

    {"resilience": {"enabled": true,
                    "checkpoint": {"keep_last_n": 3},
                    "sentinel": {"policy": "rollback"},
                    "watchdog": {"timeout_secs": 600}}}

With the block absent or disabled the compiled train step is
byte-identical to a resilience-free build (pinned in
``tests/unit/test_resilience.py``).
"""

from deepspeed_tpu.runtime.resilience import chaos  # noqa: F401
from deepspeed_tpu.runtime.resilience.integrity import (  # noqa: F401
    CheckpointCorruptionError,
    ResilientCheckpointEngine,
    atomic_write_text,
    read_verified,
    verify_tag_dir,
    write_manifest,
)
from deepspeed_tpu.runtime.resilience.manager import (  # noqa: F401
    Resilience,
    fast_forward,
)
from deepspeed_tpu.runtime.resilience.sentinel import (  # noqa: F401
    SentinelAbort,
    StepSentinel,
)
from deepspeed_tpu.runtime.resilience.topology import (  # noqa: F401
    TOPOLOGY_MANIFEST_NAME,
    TopologyShiftError,
    diff_topology,
    format_topology_diff,
    read_topology_manifest,
    write_topology_manifest,
)
from deepspeed_tpu.runtime.resilience.watchdog import HangWatchdog  # noqa: F401
