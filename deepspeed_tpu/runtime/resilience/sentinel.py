"""Step sentinel: NaN/Inf and loss-spike detection beyond the fp16 path.

The fp16 overflow machinery already skips bad steps — but only when fp16
loss scaling is on. bf16/fp32 runs (the TPU default) had ZERO protection:
a NaN storm silently corrupts the weights and every checkpoint after it.
The sentinel watches the per-step loss at each optimizer boundary and
applies the configured policy (``resilience.sentinel.policy``):

- ``warn``     — loud log + fault event, training continues;
- ``skip``     — the *in-graph* grads NaN/Inf check is force-enabled
  (the same ``has_inf_or_nan`` → skip-update path fp16 overflow uses, so
  a skipped step leaves the trajectory identical to an fp16 overflow
  skip: params/optimizer untouched, ``global_step+1``,
  ``skipped_steps+1``); the host-side sentinel reports the trip;
- ``abort``    — raise :class:`SentinelAbort` out of ``engine.step()``
  (a supervisor restarts from the last verified-good checkpoint);
- ``rollback`` — restore the last verified-good checkpoint in place and
  report how many optimizer steps the data pipeline must fast-forward.

Host-sync discipline: reading a device loss forces a sync, which would
serialize the dispatch queue. The sentinel therefore holds each boundary's
loss for ``sync_lag`` further boundaries before fetching it — by then the
value has long materialized and ``float()`` is free. ``sync_lag: 0``
checks immediately (tests / tight safety); engines that already fetched
the loss (``train_batch`` returns a float) feed the synced value in
directly so no second fetch ever happens.
"""

import math
from collections import deque
from typing import Callable, Optional

from deepspeed_tpu.utils.logging import logger


class SentinelAbort(RuntimeError):
    """Raised out of ``engine.step()`` under ``policy: abort`` (and when
    ``rollback`` exhausts ``max_rollbacks``)."""


class StepSentinel:
    """Boundary-loss monitor. ``on_trip(step, value, reason)`` is invoked
    for every detection; policy dispatch lives in the resilience manager
    (rollback needs the engine)."""

    def __init__(self, config, on_trip: Optional[Callable] = None):
        self.config = config
        self.on_trip = on_trip or (lambda step, value, reason: None)
        self._window = deque(maxlen=max(1, int(config.loss_window)))
        self._pending = deque()   # (step, device-or-host loss)
        self._last_judged = None  # a boundary is judged at most ONCE
        self.trips = []           # (step, value, reason)

    # ------------------------------------------------------------------
    def observe(self, step: int, loss):
        """Record a boundary's loss (device array or scalar) and check
        any entries older than ``sync_lag`` boundaries."""
        if loss is None:
            return
        self._pending.append((int(step), loss))
        while len(self._pending) > max(0, int(self.config.sync_lag)):
            s, v = self._pending.popleft()
            self._check(s, v)

    def observe_value(self, step: int, value: float):
        """Feed an already-synced loss (e.g. ``train_batch``'s float) —
        replaces this boundary's lagged entry entirely (the same step
        must never be judged twice)."""
        step = int(step)
        if self._pending:
            self._pending = deque((s, v) for s, v in self._pending
                                  if s != step)
        self._check(step, value)

    def drain(self):
        """Force-check everything pending (end of run / before abort)."""
        while self._pending:
            s, v = self._pending.popleft()
            self._check(s, v)

    def reset(self):
        """Forget history (after a rollback the restored trajectory must
        not be judged against the diverged window — and its rewound step
        numbers must be judgeable again)."""
        self._pending.clear()
        self._window.clear()
        self._last_judged = None

    # ------------------------------------------------------------------
    def _check(self, step: int, value):
        if self._last_judged is not None and step <= self._last_judged:
            # the synced path (observe_value) and the lagged queue can
            # both see a boundary with sync_lag=0 — one verdict per step
            return
        self._last_judged = step
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        if not math.isfinite(v):
            self._trip(step, v, "nonfinite")
            return
        factor = float(self.config.loss_spike_factor)
        if (factor > 0 and len(self._window) >= int(self.config.min_history)):
            # median baseline: one early outlier in the window must not
            # drag the threshold up (a mean would let the next spike hide
            # behind the last one)
            ordered = sorted(self._window)
            mid = len(ordered) // 2
            baseline = (ordered[mid] if len(ordered) % 2
                        else (ordered[mid - 1] + ordered[mid]) / 2.0)
            if v > factor * max(abs(baseline), 1e-8):
                self._trip(step, v, "loss_spike")
                return
        self._window.append(v)

    def _trip(self, step: int, value, reason: str):
        self.trips.append((step, value, reason))
        logger.warning(
            f"[resilience] SENTINEL TRIP at step {step}: loss={value} "
            f"({reason}); policy={self.config.policy!r}")
        self.on_trip(step, value, reason)
