"""Hang watchdog: detect stalled step progress, dump, abort cleanly.

A stuck collective (dead peer host, wedged DCN link) hangs a TPU job
*silently*: the host blocks in a device wait, no exception ever fires,
and the job burns its reservation until a human notices. The watchdog is
a daemon thread that watches host-observable step progress
(``notify(step)`` at every optimizer boundary) and, when no boundary
lands for ``timeout_secs``:

1. dumps every Python thread's stack plus the telemetry event tail to
   ``<dump_dir>/watchdog_dump_<ts>.txt`` (and the log), so the stall is
   diagnosable post-mortem;
2. emits a ``fault`` telemetry event and flushes the sink;
3. aborts: SIGTERM first (lets ``DSElasticAgent``/atexit hooks react if
   the process is not fully wedged), then ``os._exit(exit_code)`` after a
   short grace — the supervisor/scheduler restarts the job, which resumes
   from the last verified-good checkpoint.

Arming: the timer starts at the FIRST ``notify`` — the initial
multi-minute XLA compile before step 1 can never trip it. ``abort:
false`` (tests, notebooks) stops after the dump.
"""

import os
import signal
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from deepspeed_tpu.utils.logging import logger


def format_all_stacks() -> str:
    """Every live thread's Python stack (the hung collective shows up as
    the main thread blocked in a device wait)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {names.get(ident, '?')} (ident {ident}) ---")
        out.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(out)


class HangWatchdog:
    def __init__(self, *, timeout_secs: float, poll_secs: float = 0.0,
                 dump_dir: str = "./resilience", abort: bool = True,
                 exit_code: int = 43, grace_secs: float = 2.0,
                 name: str = "engine", on_dump: Optional[Callable] = None,
                 tail_fn: Optional[Callable] = None,
                 emit: Optional[Callable] = None,
                 flush: Optional[Callable] = None,
                 idle_ok: bool = False):
        self.timeout_secs = float(timeout_secs)
        self.poll_secs = float(poll_secs) if poll_secs and poll_secs > 0 \
            else min(max(self.timeout_secs / 4.0, 0.05), 10.0)
        self.dump_dir = dump_dir
        self.abort = bool(abort)
        self.exit_code = int(exit_code)
        self.grace_secs = float(grace_secs)
        self.name = name
        self.on_dump = on_dump          # (dump_text, path) -> None
        self.tail_fn = tail_fn          # () -> list of recent events
        self._emit = emit or (lambda event_name, **data: None)
        self._flush = flush or (lambda: None)
        # idle_ok: a quiet period with NO work in flight is healthy (a
        # serving engine between requests) — the timer only runs while
        # busy_begin()..busy_end() brackets something. Training mode
        # (idle_ok=False) treats ANY gap in step progress as a stall.
        self.idle_ok = bool(idle_ok)
        self.fired = False
        self.last_step = None
        self._busy = 0
        self._suspended = 0
        self._last_progress = None      # monotonic ts; None = not armed
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"ds-hang-watchdog[{self.name}]",
            daemon=True)
        self._thread.start()

    def notify(self, step: Optional[int] = None):
        """Step-boundary heartbeat: cheap (a lock + two stores). The
        first call arms the timer."""
        with self._lock:
            self._last_progress = time.monotonic()
            if step is not None:
                self.last_step = int(step)

    def suspend(self):
        """Pause the stall timer (a known-long non-step phase: checkpoint
        save/restore IO can legitimately exceed the step timeout)."""
        with self._lock:
            self._suspended += 1

    def resume(self):
        with self._lock:
            self._suspended = max(0, self._suspended - 1)
            self._last_progress = time.monotonic()  # fresh window

    def touch(self):
        """Refresh the stall timer on host-observable sub-step progress
        (a serving decode step that produced tokens without finishing any
        request). Unlike :meth:`notify` this never ARMS the watchdog — a
        long first-request compile must stay untripped."""
        with self._lock:
            if self._last_progress is not None:
                self._last_progress = time.monotonic()

    def busy_begin(self):
        """Work started (a serving request was accepted): the stall timer
        runs until the matching :meth:`busy_end`. Does NOT arm an unarmed
        watchdog — the first request carries the big XLA compile, and the
        'initial compiles can never trip it' guarantee must hold for
        serving exactly as it does for training (arming happens at the
        first COMPLETED request, via :meth:`notify`)."""
        with self._lock:
            self._busy += 1
            if self._last_progress is not None:
                self._last_progress = time.monotonic()

    def busy_end(self):
        with self._lock:
            self._busy = max(0, self._busy - 1)
            if self._last_progress is not None:
                self._last_progress = time.monotonic()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.poll_secs * 2 + 1.0)
        self._thread = None

    # ------------------------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.poll_secs):
            with self._lock:
                last = self._last_progress
                busy = self._busy
                suspended = self._suspended
            if last is None or self.fired:
                continue  # not armed yet (still compiling step 1)
            if suspended > 0:
                # long checkpoint IO etc.: healthy, keep the timer based
                with self._lock:
                    self._last_progress = time.monotonic()
                continue
            if self.idle_ok and busy == 0:
                # serving engine between requests: healthy, keep the
                # timer re-based so the NEXT request gets a full window
                with self._lock:
                    self._last_progress = time.monotonic()
                continue
            stalled = time.monotonic() - last
            if stalled >= self.timeout_secs:
                self._fire(stalled)
                if self.abort:
                    return

    def _fire(self, stalled_secs: float):
        self.fired = True
        lines = [
            f"HANG WATCHDOG [{self.name}]: no step-boundary progress for "
            f"{stalled_secs:.1f}s (timeout {self.timeout_secs:.1f}s, last "
            f"completed step {self.last_step}). A stalled collective or "
            "dead peer host is the usual cause.",
            "",
            "=== python stacks ===",
            format_all_stacks(),
        ]
        tail = []
        if self.tail_fn is not None:
            try:
                tail = list(self.tail_fn() or [])
            except Exception:
                tail = []
        if tail:
            lines += ["", "=== telemetry event tail ==="]
            lines += [repr(e) for e in tail]
        dump = "\n".join(lines)
        path = None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir, f"watchdog_dump_{int(time.time())}.txt")
            with open(path, "w") as f:
                f.write(dump + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            logger.warning(f"[resilience] watchdog dump file failed ({e}); "
                           "dump goes to the log only")
        logger.error(dump if path is None
                     else f"{lines[0]} Full dump: {path}")
        try:
            self._emit("watchdog.hang", stalled_secs=round(stalled_secs, 1),
                       timeout_secs=self.timeout_secs,
                       last_step=self.last_step, dump_path=path)
            self._flush()
        except Exception:
            pass
        if self.on_dump is not None:
            try:
                self.on_dump(dump, path)
            except Exception:
                pass
        if self.abort:
            self._abort()

    def _abort(self):
        logger.error(
            f"[resilience] watchdog aborting: SIGTERM now, hard exit "
            f"({self.exit_code}) in {self.grace_secs:.1f}s — restart and "
            "resume from the last verified-good checkpoint")
        try:
            os.kill(os.getpid(), signal.SIGTERM)
        except OSError:
            pass
        time.sleep(self.grace_secs)
        os._exit(self.exit_code)
