"""Deterministic fault injectors for the resilience test harness.

Production code never fails on purpose; proving the degradation paths
(checkpoint retry, fallback-to-last-good, sentinel trip, watchdog
dump-and-abort) therefore needs seams where faults can be injected
*deterministically*. This module is that seam:

- :func:`io_errors` — arm transient IO failures at a named injection
  point (the resilient checkpoint engine calls :func:`raise_if` around
  every save/load/commit); "fail the Nth call, M times" is exact, so a
  retry test proves the exact backoff schedule.
- :func:`corrupt_checkpoint` — flip bytes in an already-committed
  checkpoint file (bitrot / truncated blob-store upload), the failure
  integrity verification exists to catch.
- :func:`nan_batches` — wrap a batch iterator, poisoning one batch's
  float leaves with NaN at a chosen index (a bf16 NaN storm's first
  step, as the gradient path sees it).
- :func:`send_sigterm` — deliver a real SIGTERM to this process (the
  TPU preemption notice the elastic agent arms for).
- :func:`simulate_stall` — block the calling thread past a watchdog
  timeout (a hung collective, as the host observes it).
- :class:`ChaosReplica` — replica-level faults for the multi-replica
  serving router: crash at decode step N (:class:`ReplicaCrashed`),
  transient flaky step/submit, stall, slow decode.
- :class:`FlakyFactory` — faults at the fleet manager's
  ``ReplicaFactory`` scale-up seam: N failed builds (the autoscaler's
  exponential-backoff food), injectable build stalls.

All injectors are process-local and OFF by default; :func:`raise_if`
costs one module-level ``if`` when nothing is armed.
"""

import os
import signal
import threading
import time
from typing import Dict, Iterable, Optional

import numpy as np

_LOCK = threading.Lock()
_FAULTS: Dict[str, "_IOFault"] = {}


class ChaosIOError(OSError):
    """The injected transient IO error (an OSError subclass so retry
    paths treat it exactly like a real flaky filesystem/blob store)."""


class _IOFault:
    def __init__(self, at_call: int, times: int, exc: type):
        self.at_call = int(at_call)   # 1-indexed call number to start failing
        self.times = int(times)       # how many consecutive calls fail
        self.exc = exc
        self.calls = 0                # calls observed at this point
        self.raised = 0               # failures actually injected

    def should_raise(self) -> bool:
        self.calls += 1
        if self.at_call <= self.calls < self.at_call + self.times:
            self.raised += 1
            return True
        return False


def io_errors(point: str, at_call: int = 1, times: int = 1,
              exc: type = ChaosIOError) -> "_Armed":
    """Arm ``times`` consecutive failures at injection ``point`` starting
    with its ``at_call``-th call (1-indexed). Returns a context manager /
    handle; the fault also disarms process-wide via :func:`clear`.

    Known points: ``"ckpt.save"``, ``"ckpt.load"``, ``"ckpt.commit"``.
    """
    fault = _IOFault(at_call, times, exc)
    with _LOCK:
        _FAULTS[point] = fault
    return _Armed(point, fault)


class _Armed:
    def __init__(self, point: str, fault: _IOFault):
        self.point = point
        self.fault = fault

    @property
    def raised(self) -> int:
        return self.fault.raised

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        with _LOCK:
            if _FAULTS.get(self.point) is self.fault:
                del _FAULTS[self.point]
        return False


def raise_if(point: str, detail: str = ""):
    """Injection hook — called by the resilient checkpoint engine around
    each IO operation. No-op unless a fault is armed at ``point``."""
    if not _FAULTS:  # fast path: chaos never armed in production
        return
    with _LOCK:
        fault = _FAULTS.get(point)
        if fault is None:
            return
        fire = fault.should_raise()
    if fire:
        raise fault.exc(
            f"chaos: injected IO error at {point!r}"
            + (f" ({detail})" if detail else "")
            + f" [call {fault.calls}]")


def clear():
    """Disarm every injector (test teardown)."""
    with _LOCK:
        _FAULTS.clear()


# ----------------------------------------------------------------------
# post-commit corruption (bitrot / partial upload)
def corrupt_checkpoint(tag_dir: str, filename: Optional[str] = None,
                       offset: int = 0, nbytes: int = 8) -> str:
    """Flip ``nbytes`` bytes of one payload file inside a committed
    checkpoint tag directory (the largest file when ``filename`` is not
    given — the array payload, where silent corruption hurts most).
    Returns the path corrupted."""
    if filename is not None:
        target = os.path.join(tag_dir, filename)
    else:
        candidates = []
        for base, _, files in os.walk(tag_dir):
            for fn in files:
                if fn.startswith("."):
                    continue  # never the integrity manifest itself
                p = os.path.join(base, fn)
                candidates.append((os.path.getsize(p), p))
        if not candidates:
            raise FileNotFoundError(f"no files to corrupt under {tag_dir}")
        target = max(candidates)[1]
    size = os.path.getsize(target)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {target}")
    offset = min(max(0, offset), max(0, size - nbytes))
    with open(target, "r+b") as f:
        f.seek(offset)
        chunk = f.read(nbytes)
        f.seek(offset)
        f.write(bytes((b ^ 0xFF) for b in chunk))
        f.flush()
        os.fsync(f.fileno())
    return target


def truncate_file(path: str, keep_bytes: int = 0):
    """Simulate a crash mid-write: keep only the first ``keep_bytes``."""
    with open(path, "r+b") as f:
        f.truncate(int(keep_bytes))


# ----------------------------------------------------------------------
# NaN gradients at step K (bf16 NaN storm)
def nan_batches(batches: Iterable, at: int, leaf_index: int = 0):
    """Yield from ``batches``, replacing the ``at``-th batch's (0-indexed)
    first float leaf (or ``leaf_index``-th) with NaNs. Gradients of that
    micro-step are NaN — exactly what the step sentinel must catch."""
    import jax

    for i, batch in enumerate(batches):
        if i == at:
            leaves, treedef = jax.tree_util.tree_flatten(batch)
            poisoned, float_seen = [], 0
            for leaf in leaves:
                arr = np.asarray(leaf)
                if arr.dtype.kind == "f" and float_seen == leaf_index:
                    arr = np.full_like(arr, np.nan)
                    float_seen += 1
                elif arr.dtype.kind == "f":
                    float_seen += 1
                poisoned.append(arr)
            batch = jax.tree_util.tree_unflatten(treedef, poisoned)
        yield batch


def poison_batch(batch, leaf_index: int = 0):
    """NaN-poison one batch directly (the single-batch form of
    :func:`nan_batches`)."""
    return next(nan_batches([batch], at=0, leaf_index=leaf_index))


# ----------------------------------------------------------------------
# replica-level injectors (multi-replica serving front door)
class ReplicaCrashed(RuntimeError):
    """Fatal replica death (SIGKILLed engine process, unrecoverable
    device error). Routers treat any exception whose ``replica_dead``
    attribute is true as unrecoverable: the replica goes DEAD instead of
    merely tripping its breaker."""

    replica_dead = True


class ChaosReplica:
    """Deterministic replica-level fault injection for the serving
    router: wraps anything with the ``ServingEngine`` surface,
    delegating transparently until the armed fault fires.

    - ``crash_at_step=N`` — the Nth ``step()`` call (1-indexed, and every
      call after it) raises :class:`ReplicaCrashed` BEFORE the wrapped
      engine runs: the replica died mid-decode with requests in flight.
    - ``fail_step_at=N, fail_step_times=M`` — M consecutive ``step()``
      calls starting at the Nth raise transient :class:`ChaosIOError`
      (a flaky interconnect: the breaker's consecutive-failure food).
    - ``fail_submit_at=N, fail_submit_times=M`` — same, for ``submit()``
      (a flaky admission RPC; the router retries on another replica).
    - ``stall_at_step=N, stall_secs=S`` — the Nth step blocks for S
      seconds before running (a wedged collective, as the router's
      host-side stall timer observes it).
    - ``slow_decode_secs=S`` — EVERY step takes S extra seconds (a
      thermally-throttled or mis-sharded replica: the soft DEGRADED
      signal, not a trip).
    - ``crash_between_draft_and_commit=N`` — the Nth ``step()`` runs the
      wrapped engine with a one-shot :class:`ReplicaCrashed` armed at
      the serving engine's ``"serving.spec_commit"`` seam: a
      speculative-decoding replica dies AFTER the verify dispatch but
      BEFORE any token of the window commits — the hardest failover
      moment, where the exactly-once splice must see zero speculative
      tokens (the fault is armed only around this one delegated call,
      so a co-resident replica stepping through the same seam is never
      hit).
    - ``crash_during_migration=N`` — the Nth ``export_sequence()`` call
      (one-shot) performs the REAL export, then raises
      :class:`ReplicaCrashed`: the source dies between export and the
      target's table commit — the hardest migration moment, where the
      move must abort with the target's allocation released and the
      orchestrator falls back to replay with exactly-once delivery.
      The replica is dead from that point (later ``step()`` calls
      crash, as a killed process would).
    - ``flaky_transfer_at=N, flaky_transfer_times=M`` — M consecutive
      migrations starting with the Nth export lose their wire transfer:
      a one-shot transient :class:`ChaosIOError` armed at the
      ``"serving.migration.transfer"`` seam right after each export, so
      the fault lands between export and import — source untouched, the
      caller retries or replays.

    ``sleep`` is injectable so host-side tests drive stalls through a
    fake clock instead of wall time.
    """

    def __init__(self, replica, crash_at_step: int = 0,
                 fail_step_at: int = 0, fail_step_times: int = 1,
                 fail_submit_at: int = 0, fail_submit_times: int = 1,
                 stall_at_step: int = 0, stall_secs: float = 0.0,
                 slow_decode_secs: float = 0.0,
                 crash_between_draft_and_commit: int = 0,
                 crash_during_migration: int = 0,
                 flaky_transfer_at: int = 0, flaky_transfer_times: int = 1,
                 sleep=time.sleep):
        self.replica = replica
        self.crash_at_step = int(crash_at_step)
        self.crash_between_draft_and_commit = int(
            crash_between_draft_and_commit)
        self.fail_step_at = int(fail_step_at)
        self.fail_step_times = int(fail_step_times)
        self.fail_submit_at = int(fail_submit_at)
        self.fail_submit_times = int(fail_submit_times)
        self.stall_at_step = int(stall_at_step)
        self.stall_secs = float(stall_secs)
        self.slow_decode_secs = float(slow_decode_secs)
        self.crash_during_migration = int(crash_during_migration)
        self.flaky_transfer_at = int(flaky_transfer_at)
        self.flaky_transfer_times = int(flaky_transfer_times)
        self.sleep = sleep
        self.steps = 0
        self.submits = 0
        self.migration_exports = 0

    def submit(self, *args, **kwargs):
        self.submits += 1
        if (self.fail_submit_at and self.fail_submit_at <= self.submits
                < self.fail_submit_at + self.fail_submit_times):
            raise ChaosIOError(
                f"chaos: flaky submit [call {self.submits}]")
        return self.replica.submit(*args, **kwargs)

    def step(self):
        self.steps += 1
        if self.crash_at_step and self.steps >= self.crash_at_step:
            raise ReplicaCrashed(
                f"chaos: replica crashed at step {self.steps}")
        if (self.fail_step_at and self.fail_step_at <= self.steps
                < self.fail_step_at + self.fail_step_times):
            raise ChaosIOError(f"chaos: flaky step [call {self.steps}]")
        if self.stall_at_step and self.steps == self.stall_at_step \
                and self.stall_secs:
            self.sleep(self.stall_secs)
        if self.slow_decode_secs:
            self.sleep(self.slow_decode_secs)
        if (self.crash_between_draft_and_commit
                and self.steps >= self.crash_between_draft_and_commit):
            # one-shot, scoped to THIS delegated call: the wrapped
            # engine's raise_if("serving.spec_commit") fires between its
            # verify dispatch and commit loop
            with io_errors("serving.spec_commit", at_call=1,
                           exc=ReplicaCrashed):
                return self.replica.step()
        return self.replica.step()

    def __getattr__(self, name):
        # gauges/stats/pending/buckets/telemetry/... delegate untouched.
        # getattr-first keeps hasattr() semantics honest: a wrapped
        # replica WITHOUT the migration surface must still read as not
        # having one (the router's migrate-vs-replay probe depends on it)
        attr = getattr(self.replica, name)
        if name == "export_sequence" and (self.crash_during_migration
                                          or self.flaky_transfer_at):
            def export(request_id):
                self.migration_exports += 1
                n = self.migration_exports
                out = attr(request_id)
                if n == self.crash_during_migration:
                    # the export left the process; the process died —
                    # the fault lands between export and the target's
                    # table commit, and the replica stays dead
                    self.crash_at_step = max(1, self.steps)
                    raise ReplicaCrashed(
                        f"chaos: replica crashed mid-migration "
                        f"[export {n}]")
                if (self.flaky_transfer_at and self.flaky_transfer_at
                        <= n < self.flaky_transfer_at
                        + self.flaky_transfer_times):
                    # scoped one-shot: the orchestrator's very next
                    # "serving.migration.transfer" seam is THIS move's
                    io_errors("serving.migration.transfer", at_call=1)
                return out

            return export
        return attr


class FlakyFactory:
    """Deterministic faults for the fleet manager's ``ReplicaFactory``
    seam: wraps a factory (or a zero-arg builder callable); the first
    ``fail_times`` ``build()`` calls raise transient
    :class:`ChaosIOError` (the autoscaler must back off exponentially,
    not hammer), and ``stall_secs`` blocks before every build through
    the injectable ``sleep`` (a cold container pull, as the fleet
    observes it — drive it with a fake clock in tests)."""

    def __init__(self, factory, fail_times: int = 0,
                 stall_secs: float = 0.0, sleep=time.sleep):
        self.factory = factory
        self.fail_times = int(fail_times)
        self.stall_secs = float(stall_secs)
        self.sleep = sleep
        self.builds = 0     # build() calls observed
        self.failures = 0   # failures actually injected

    @property
    def warm(self) -> bool:
        return bool(getattr(self.factory, "warm", False))

    def build(self):
        self.builds += 1
        if self.stall_secs:
            self.sleep(self.stall_secs)
        if self.builds <= self.fail_times:
            self.failures += 1
            raise ChaosIOError(
                f"chaos: replica factory failed [build {self.builds}]")
        build = getattr(self.factory, "build", None)
        return build() if build is not None else self.factory()


# ----------------------------------------------------------------------
# preemption + stall
def send_sigterm():
    """Deliver a real SIGTERM to this process — the TPU scheduler's
    preemption notice, as ``DSElasticAgent`` receives it."""
    os.kill(os.getpid(), signal.SIGTERM)


def preempt_at_step(at_step: int, deliver=send_sigterm):
    """Arm a deterministic preemption for the elastic chaos scenarios:
    returns a ``tick()`` to call once per optimizer step; the
    ``at_step``-th call (1-indexed) delivers the preemption notice
    (default: a REAL SIGTERM, exactly what the TPU scheduler sends —
    pass ``agent.signal_preemption`` for signal-free tests). ``tick``
    returns True on the call that fired; ``tick.state`` exposes
    ``{"calls", "fired"}`` for assertions."""
    state = {"calls": 0, "fired": False}

    def tick() -> bool:
        state["calls"] += 1
        if state["calls"] == int(at_step) and not state["fired"]:
            state["fired"] = True
            deliver()
            return True
        return False

    tick.state = state
    return tick


def simulate_stall(seconds: float):
    """Block the calling thread (a hung collective, as the host observes
    it): step-boundary progress stops while the watchdog keeps polling."""
    time.sleep(float(seconds))
