"""Checkpoint topology manifest — the contract that makes a checkpoint
restorable onto a *different* mesh on purpose instead of by accident.

The elastic scenario (ISSUE 5): a preempted 8-device job gets restarted
on a 4-device slice. GSPMD (arXiv:2105.04663) makes sharding a
compile-time annotation over logical arrays, so the on-disk layout need
not dictate the resume topology — but only if the checkpoint *records*
what topology produced it. Every commit made with elasticity enabled
therefore writes ``topology.json`` alongside PR 3's integrity manifest:

- mesh axes/shape, world size, process count;
- ZeRO stage and micro-batch/GAS geometry (the batch triangle the
  restarted job must keep solving to the SAME global batch);
- per-tensor logical shape + dtype + partition spec for params and
  optimizer state (``runtime/zero/partition.spec_entries`` format);
- data-pipeline cursor (loader ``state_dict``) + step counters + RNG —
  the sample-exact replay anchor.

At load, the manifest is diffed against the live engine
(:func:`diff_topology`); an impossible reshard — a tensor whose logical
shape/dtype no longer matches, a missing tensor — raises
:class:`TopologyShiftError` carrying the structured saved-vs-current
diff, never a shape error from deep inside jax. A *possible* reshard
(mesh/world/stage changed, tensors intact) proceeds:
``jax.make_array_from_callback`` materializes each logical tensor under
the current mesh's sharding, reading only the slices this host's shards
need (``checkpoint_engine.LazyNpz``).
"""

import json
import os
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

TOPOLOGY_MANIFEST_NAME = "topology.json"
TOPOLOGY_MANIFEST_VERSION = 1


class TopologyShiftError(RuntimeError):
    """Resharding the checkpoint onto the current topology is impossible
    (or unsafe). Carries the structured saved-vs-current diff so launch
    tooling can render it; deliberately NOT a
    :class:`CheckpointCorruptionError` — falling back to an older
    checkpoint cannot fix a topology mismatch, so the resilience
    fallback chain must not swallow it."""

    def __init__(self, message: str, saved: Optional[Dict] = None,
                 current: Optional[Dict] = None,
                 diff: Optional[Dict] = None):
        super().__init__(message)
        self.saved = saved or {}
        self.current = current or {}
        self.diff = diff or {}


# ----------------------------------------------------------------------
# read / write
def write_topology_manifest(checkpoint_engine, tag_dir: str,
                            manifest: Dict) -> str:
    """Publish ``manifest`` as ``<tag_dir>/topology.json`` through the
    checkpoint engine's ``save_text`` seam (so it stages under the
    tiered engine's atomic publish and rides the integrity layer's
    retry/chaos seams, and — written before ``commit`` — is hashed into
    PR 3's integrity manifest like any payload file)."""
    path = os.path.join(tag_dir, TOPOLOGY_MANIFEST_NAME)
    checkpoint_engine.save_text(
        path, json.dumps(manifest, indent=1, sort_keys=True))
    return path


def read_topology_manifest(tag_dir: str) -> Optional[Dict]:
    """The topology manifest of a committed tag directory, or ``None``
    for a pre-elastic checkpoint (no manifest — loads take the legacy
    path unchanged). An unreadable manifest is loud: a half-written
    topology record must not silently demote an elastic restore."""
    path = os.path.join(tag_dir, TOPOLOGY_MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (ValueError, OSError) as e:
        raise TopologyShiftError(
            f"checkpoint {tag_dir!r}: topology manifest unreadable ({e}) — "
            "the tag was saved with elasticity enabled but its topology "
            "record is damaged; verify the checkpoint (integrity manifest) "
            "or load with an explicit same-topology engine")


# ----------------------------------------------------------------------
# diff / validate
def _mesh_desc(manifest: Dict) -> Dict:
    return manifest.get("mesh", {}) or {}


def _normalize_axes(axes: Optional[Dict]) -> Dict[str, int]:
    """Canonical view of a manifest's mesh-axes dict: alias names fold
    ("model" -> "tp" — pre-3-axis checkpoints restore onto the renamed
    axis without a phantom diff) and size-1 axes drop (a 5-axis-era
    manifest without "fsdp" equals a new one carrying fsdp=1)."""
    from deepspeed_tpu.utils.fingerprint import normalize_mesh_axes

    return normalize_mesh_axes(axes)


def diff_topology(saved: Dict, current: Dict) -> Dict:
    """Structured saved-vs-current comparison. ``changed`` lists benign
    shifts (mesh axes — rendered axis-by-axis, world size, ZeRO stage,
    batch geometry — the reshard path handles those); ``fatal`` lists
    differences no reshard can bridge (tensor set/shape/dtype
    mismatches)."""
    changed: Dict[str, Any] = {}
    fatal: Dict[str, Any] = {}

    s_mesh, c_mesh = _mesh_desc(saved), _mesh_desc(current)
    s_axes = _normalize_axes(s_mesh.get("axes"))
    c_axes = _normalize_axes(c_mesh.get("axes"))
    # axis-by-axis: a tp=1 -> tp=2 restore renders as "mesh.axes.tp",
    # not an opaque whole-dict swap
    for axis in sorted(set(s_axes) | set(c_axes)):
        sv, cv = s_axes.get(axis, 1), c_axes.get(axis, 1)
        if sv != cv:
            changed[f"mesh.axes.{axis}"] = {"saved": sv, "current": cv}
    for field in ("world_size", "process_count"):
        sv, cv = s_mesh.get(field), c_mesh.get(field)
        if sv != cv:
            changed[f"mesh.{field}"] = {"saved": sv, "current": cv}
    if saved.get("zero_stage") != current.get("zero_stage"):
        changed["zero_stage"] = {"saved": saved.get("zero_stage"),
                                 "current": current.get("zero_stage")}
    s_batch, c_batch = saved.get("batch", {}) or {}, current.get("batch", {}) or {}
    for field in sorted(set(s_batch) | set(c_batch)):
        if s_batch.get(field) != c_batch.get(field):
            changed[f"batch.{field}"] = {"saved": s_batch.get(field),
                                         "current": c_batch.get(field)}

    s_t = saved.get("tensors") or {}
    c_t = current.get("tensors") or {}
    if s_t and c_t:
        missing_cur = sorted(set(s_t) - set(c_t))
        missing_saved = sorted(set(c_t) - set(s_t))
        if missing_cur:
            fatal["tensors_missing_in_current"] = missing_cur
        if missing_saved:
            fatal["tensors_missing_in_saved"] = missing_saved
        shape_mm, dtype_mm, spec_changed = {}, {}, {}
        for k in sorted(set(s_t) & set(c_t)):
            se, ce = s_t[k], c_t[k]
            if list(se.get("shape", [])) != list(ce.get("shape", [])):
                shape_mm[k] = {"saved": se.get("shape"),
                               "current": ce.get("shape")}
            elif se.get("dtype") != ce.get("dtype"):
                dtype_mm[k] = {"saved": se.get("dtype"),
                               "current": ce.get("dtype")}
            elif se.get("spec") != ce.get("spec"):
                spec_changed[k] = {"saved": se.get("spec"),
                                   "current": ce.get("spec")}
        if shape_mm:
            fatal["tensor_shape_mismatch"] = shape_mm
        if dtype_mm:
            fatal["tensor_dtype_mismatch"] = dtype_mm
        if spec_changed:
            changed["tensor_spec_changed"] = len(spec_changed)
    return {"changed": changed, "fatal": fatal}


def format_topology_diff(diff: Dict, limit: int = 8) -> str:
    """Human-readable rendering of :func:`diff_topology` output."""
    lines: List[str] = []
    for kind in ("fatal", "changed"):
        entries = diff.get(kind) or {}
        for key, val in entries.items():
            if isinstance(val, dict) and set(val) == {"saved", "current"}:
                lines.append(f"  [{kind}] {key}: saved={val['saved']} -> "
                             f"current={val['current']}")
            elif isinstance(val, dict):
                shown = list(val.items())[:limit]
                for name, mm in shown:
                    lines.append(f"  [{kind}] {key} {name}: "
                                 f"saved={mm.get('saved')} -> "
                                 f"current={mm.get('current')}")
                if len(val) > limit:
                    lines.append(f"  [{kind}] {key}: ... and "
                                 f"{len(val) - limit} more")
            elif isinstance(val, list):
                shown = ", ".join(val[:limit])
                more = f" (+{len(val) - limit} more)" if len(val) > limit else ""
                lines.append(f"  [{kind}] {key}: {shown}{more}")
            else:
                lines.append(f"  [{kind}] {key}: {val}")
    return "\n".join(lines) if lines else "  (identical topologies)"


def validate_reshard(saved: Dict, current: Dict, where: str) -> Dict:
    """Raise :class:`TopologyShiftError` (with the full structured diff)
    when the saved checkpoint cannot be materialized under the current
    topology; return the diff otherwise so callers can log/emit it."""
    diff = diff_topology(saved, current)
    if diff["fatal"]:
        raise TopologyShiftError(
            f"cannot reshard checkpoint {where}: the saved topology is "
            "incompatible with the current engine —\n"
            + format_topology_diff(diff)
            + "\n(the tensor set/shapes/dtypes must match; mesh/world/"
            "ZeRO-stage changes alone are reshardable)",
            saved=saved, current=current, diff=diff)
    if diff["changed"]:
        logger.info(
            f"[elastic] topology shift at {where}:\n"
            + format_topology_diff(diff))
    return diff


def topology_shifted(diff: Dict) -> bool:
    """True when the mesh/world actually changed (vs. a same-topology
    resume) — the bit the ``topology`` telemetry event reports."""
    changed = diff.get("changed") or {}
    return any(k.startswith("mesh.") for k in changed)
