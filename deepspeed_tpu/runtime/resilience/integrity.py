"""Checkpoint integrity: checksum manifests, verified-good registry,
retry-with-backoff, retention — the storage half of the resilience layer.

The problem (ISSUE 3): ``checkpoint_engine.commit()`` returned ``True``
unconditionally — nothing ever proved the bytes on disk are the bytes
that were written, a corrupt/partial ``latest`` checkpoint crashed every
future resume, and a transient blob-store error killed the save outright.

This module adds, config-gated (``resilience.checkpoint``):

- **manifest commit** — :class:`ResilientCheckpointEngine` wraps any
  inner engine (Array/Orbax/Sharded/Tiered); its ``commit`` first drains
  the inner commit (which publishes/barriers), then rank 0 walks the tag
  directory, sha256s every payload file, and atomically writes
  ``.integrity.json``. A checkpoint without a matching manifest is never
  treated as verified-good.
- **verify-on-load** — before any bytes deserialize, the manifest is
  re-checked against the files; a mismatch raises
  :class:`CheckpointCorruptionError` naming the offending file, and the
  engine's load path falls back down the verified-good chain.
- **verified-good registry** — ``<save_dir>/.resilience/verified.json``
  records tags in commit order; it is the fallback chain for resume and
  the ordering for retention.
- **retry with exponential backoff** — every save/load IO call retries
  transient ``OSError``s (never ``FileNotFoundError`` — a missing tag is
  an answer, not a flake).
- **keep-last-N retention** — prunes old *verified* tags only, and never
  the newest verified-good tag nor the elastic agent's ``preempt`` tag.

Chaos seams (:mod:`deepspeed_tpu.runtime.resilience.chaos`) are threaded
through every IO call so the test suite can prove each path end-to-end.
"""

import hashlib
import json
import os
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (
    ArrayCheckpointEngine,
    CheckpointEngine,
    atomic_write_text,  # noqa: F401 — re-exported: the resilience
    # layer's pointer/manifest/registry writes share the engine's
    # durable-text primitive
    fsync_dir,
)
from deepspeed_tpu.runtime.resilience import chaos
from deepspeed_tpu.utils.logging import log_dist, logger

MANIFEST_NAME = ".integrity.json"
REGISTRY_DIR = ".resilience"
REGISTRY_NAME = "verified.json"
# tags retention must never touch regardless of age (the elastic agent's
# preemption checkpoint is consumed on restore, not superseded by count)
PROTECTED_TAGS = ("preempt",)


class CheckpointCorruptionError(RuntimeError):
    """Integrity verification failed: on-disk bytes do not match the
    manifest written at commit time."""


# ----------------------------------------------------------------------
# crash-safe small-file writes (the `latest` pointer / preempt marker fix)
def available_tags(load_dir: str) -> List[str]:
    """Checkpoint tag directories actually present in ``load_dir``
    (engine-internal dirs — staging, the resilience registry, stranded
    ``.replaced`` versions — are not user-loadable tags)."""
    try:
        entries = sorted(os.listdir(load_dir))
    except OSError:
        return []
    return [e for e in entries
            if os.path.isdir(os.path.join(load_dir, e))
            and not e.startswith(".") and not e.endswith(".replaced")]


def missing_tag_error(load_dir: str, tag, via: str) -> FileNotFoundError:
    """A clear missing-tag error naming the tags actually present —
    never a cryptic npz/orbax exception (shared by the training engines)."""
    present = available_tags(load_dir)
    listing = ", ".join(repr(t) for t in present) if present else "(none)"
    return FileNotFoundError(
        f"checkpoint {via} but {os.path.join(load_dir, str(tag))!r} "
        f"does not exist; tags present in {load_dir!r}: {listing}")


# ----------------------------------------------------------------------
# retry with exponential backoff
def retry_io(fn: Callable, *, retries: int, backoff_secs: float, what: str,
             on_retry: Optional[Callable] = None):
    """Run ``fn`` retrying transient ``OSError``s up to ``retries`` times
    with exponential backoff. ``FileNotFoundError``/``IsADirectoryError``
    are answers (wrong path), not flakes — they propagate immediately."""
    attempt = 0
    while True:
        try:
            return fn()
        except (FileNotFoundError, IsADirectoryError, NotADirectoryError):
            raise
        except OSError as e:
            attempt += 1
            if attempt > max(0, int(retries)):
                raise
            delay = float(backoff_secs) * (2 ** (attempt - 1))
            logger.warning(f"[resilience] {what}: transient IO error "
                           f"({e}); retry {attempt}/{retries} in "
                           f"{delay:.2f}s")
            if on_retry is not None:
                on_retry(attempt, delay, e)
            if delay > 0:
                time.sleep(delay)


# ----------------------------------------------------------------------
# manifest build / verify
def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def build_manifest(tag_dir: str) -> Dict:
    """Per-file sha256 + byte size of every payload file under the tag
    directory (dot-files — the manifest itself, orbax lockfiles — are
    metadata, not payload)."""
    files = {}
    for base, dirs, names in os.walk(tag_dir):
        dirs[:] = [d for d in dirs if not d.startswith(".")]
        for fn in sorted(names):
            if fn.startswith("."):
                continue
            p = os.path.join(base, fn)
            rel = os.path.relpath(p, tag_dir)
            files[rel] = {"sha256": file_sha256(p),
                          "bytes": os.path.getsize(p)}
    return {"version": 1, "created": round(time.time(), 3), "files": files}


def write_manifest(tag_dir: str) -> Dict:
    """Hash the tag directory and atomically publish its manifest (the
    real ``commit()`` step)."""
    chaos.raise_if("ckpt.commit", tag_dir)
    manifest = build_manifest(tag_dir)
    atomic_write_text(os.path.join(tag_dir, MANIFEST_NAME),
                      json.dumps(manifest, indent=1, sort_keys=True))
    return manifest


def verify_tag_dir(tag_dir: str) -> str:
    """Re-check a tag directory against its manifest.

    Returns ``"ok"`` (manifest present, every file matches) or
    ``"unverified"`` (no manifest — a pre-resilience checkpoint; loadable
    but never verified-good). Raises :class:`CheckpointCorruptionError`
    naming the first mismatching file otherwise.
    """
    mpath = os.path.join(tag_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return "unverified"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        raise CheckpointCorruptionError(
            f"checkpoint {tag_dir!r}: integrity manifest unreadable ({e})")
    for rel, want in sorted((manifest.get("files") or {}).items()):
        p = os.path.join(tag_dir, rel)
        if not os.path.exists(p):
            raise CheckpointCorruptionError(
                f"checkpoint {tag_dir!r}: file {rel!r} listed in the "
                "integrity manifest is missing")
        size = os.path.getsize(p)
        if size != want.get("bytes"):
            raise CheckpointCorruptionError(
                f"checkpoint {tag_dir!r}: file {rel!r} is {size} bytes, "
                f"manifest says {want.get('bytes')} (truncated write?)")
        digest = file_sha256(p)
        if digest != want.get("sha256"):
            raise CheckpointCorruptionError(
                f"checkpoint {tag_dir!r}: file {rel!r} checksum mismatch "
                f"({digest[:12]}… != manifest {str(want.get('sha256'))[:12]}…)")
    return "ok"


# ----------------------------------------------------------------------
# verified-good registry (per save_dir, commit order)
def _registry_path(save_dir: str) -> str:
    return os.path.join(save_dir, REGISTRY_DIR, REGISTRY_NAME)


def read_verified(save_dir: str) -> List[str]:
    """Tags with a committed manifest, oldest → newest."""
    path = _registry_path(save_dir)
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            tags = json.load(f)
        return [str(t) for t in tags] if isinstance(tags, list) else []
    except (ValueError, OSError):
        logger.warning(f"[resilience] verified-good registry at {path!r} "
                       "unreadable; treating as empty")
        return []


def _write_verified(save_dir: str, tags: List[str]):
    os.makedirs(os.path.join(save_dir, REGISTRY_DIR), exist_ok=True)
    atomic_write_text(_registry_path(save_dir), json.dumps(tags))


def record_verified(save_dir: str, tag: str) -> List[str]:
    tags = [t for t in read_verified(save_dir) if t != str(tag)]
    tags.append(str(tag))
    _write_verified(save_dir, tags)
    return tags


# ----------------------------------------------------------------------
class ResilientCheckpointEngine(CheckpointEngine):
    """Integrity wrapper around any checkpoint engine.

    ``save``/``load`` gain retry-with-backoff and chaos seams; ``commit``
    gains the manifest write + verified-good registry + retention;
    ``load`` gains verify-before-deserialize. Transparent otherwise:
    ``supports_sharded``/``load_sharded``/``aux_engine`` forward to the
    inner engine, so the wrapper composes with the Array, Orbax, Sharded,
    and Tiered tiers unchanged.
    """

    def __init__(self, inner: CheckpointEngine, config, emit=None):
        super().__init__(None)
        self._inner = inner
        self._cfg = config
        # fault-event emitter: (name, **data) -> None; wired to the
        # resilience manager (telemetry "fault" events + tail)
        self._emit = emit or (lambda name, **data: None)
        self._roots = set()           # save_dirs written this round
        self._verified_ok = set()     # tag dirs verified ok this process

    # -- transparent capability surface --------------------------------
    @property
    def supports_sharded(self):
        return getattr(self._inner, "supports_sharded", False)

    @property
    def supports_lazy(self):
        return getattr(self._inner, "supports_lazy", False)

    @property
    def aux_engine(self):
        """Aux (consolidated npz/json) saves ride the same retry/chaos
        seams; staging semantics stay the inner engine's (the Tiered
        tier's aux staging is preserved by wrapping ITS aux engine)."""
        inner_aux = getattr(self._inner, "aux_engine", None) \
            or ArrayCheckpointEngine()
        outer = self

        class _Aux(CheckpointEngine):
            def save(self, state_dict, path):
                outer._guarded_save(inner_aux, state_dict, path)

            def load(self, path, map_location=None):
                return outer._guarded_load(inner_aux, path, map_location)

        return _Aux()

    @staticmethod
    def _split(path):
        """'<save_dir>/<tag>/<name>' -> (save_dir, tag, name)."""
        tag_dir, name = os.path.split(path)
        save_dir, tag = os.path.split(tag_dir)
        return save_dir or ".", tag, name

    def create(self, tag):
        self._inner.create(tag)

    def makedirs(self, path, exist_ok=False):
        self._inner.makedirs(path, exist_ok=exist_ok)

    # -- save / load with retry + chaos --------------------------------
    def _on_retry(self, op, path):
        def hook(attempt, delay, exc):
            self._emit("ckpt.retry", op=op, path=path, attempt=attempt,
                       delay_secs=round(delay, 3), error=str(exc)[:200])

        return hook

    def _guarded_save(self, engine, state_dict, path):
        save_dir, tag, _ = self._split(path)
        self._roots.add(save_dir)
        # re-saving a tag invalidates any cached verification verdict —
        # the bytes on disk are about to change
        self._verified_ok.discard(
            os.path.realpath(os.path.join(save_dir, tag)))

        def do():
            chaos.raise_if("ckpt.save", path)
            return engine.save(state_dict, path)

        return retry_io(do, retries=self._cfg.retries,
                        backoff_secs=self._cfg.retry_backoff_secs,
                        what=f"save {path!r}",
                        on_retry=self._on_retry("save", path))

    def _guarded_load(self, engine, path, map_location=None, sharded=False,
                      abstract_tree=None):
        save_dir, tag, _ = self._split(path)
        self.verify(os.path.join(save_dir, tag))

        def do():
            chaos.raise_if("ckpt.load", path)
            if sharded:
                return engine.load_sharded(path, abstract_tree)
            return engine.load(path, map_location=map_location)

        return retry_io(do, retries=self._cfg.retries,
                        backoff_secs=self._cfg.retry_backoff_secs,
                        what=f"load {path!r}",
                        on_retry=self._on_retry("load", path))

    def save(self, state_dict, path):
        return self._guarded_save(self._inner, state_dict, path)

    def save_text(self, path, text):
        """Sidecar metadata (topology manifest) rides the same retry +
        chaos seams and the same verdict-invalidation as payload saves."""
        save_dir, tag, _ = self._split(path)
        self._roots.add(save_dir)
        self._verified_ok.discard(
            os.path.realpath(os.path.join(save_dir, tag)))

        def do():
            chaos.raise_if("ckpt.save", path)
            return self._inner.save_text(path, text)

        return retry_io(do, retries=self._cfg.retries,
                        backoff_secs=self._cfg.retry_backoff_secs,
                        what=f"save {path!r}",
                        on_retry=self._on_retry("save", path))

    def save_bytes(self, path, blob):
        """Binary sidecars (the AOT program bundle) ride the same retry
        + chaos seams and the same verdict-invalidation as text
        sidecars."""
        save_dir, tag, _ = self._split(path)
        self._roots.add(save_dir)
        self._verified_ok.discard(
            os.path.realpath(os.path.join(save_dir, tag)))

        def do():
            chaos.raise_if("ckpt.save", path)
            return self._inner.save_bytes(path, blob)

        return retry_io(do, retries=self._cfg.retries,
                        backoff_secs=self._cfg.retry_backoff_secs,
                        what=f"save {path!r}",
                        on_retry=self._on_retry("save", path))

    def load(self, path, map_location=None):
        return self._guarded_load(self._inner, path, map_location)

    def load_sharded(self, path, abstract_tree):
        return self._guarded_load(self._inner, path, sharded=True,
                                  abstract_tree=abstract_tree)

    def load_lazy(self, path):
        """Slice-addressable load (reshard-at-load): verify-before-read
        + retry on the reader OPEN. Per-slice reads after open are
        memmap page faults — not an IO seam this layer can wrap."""
        save_dir, tag, _ = self._split(path)
        self.verify(os.path.join(save_dir, tag))

        def do():
            chaos.raise_if("ckpt.load", path)
            return self._inner.load_lazy(path)

        return retry_io(do, retries=self._cfg.retries,
                        backoff_secs=self._cfg.retry_backoff_secs,
                        what=f"load {path!r}",
                        on_retry=self._on_retry("load", path))

    # -- verify ---------------------------------------------------------
    def verify(self, tag_dir: str) -> str:
        """Verify a tag directory (cached per process once it passes).
        Raises :class:`CheckpointCorruptionError` on mismatch.

        Multi-process: rank 0 alone hashes (a shared filesystem holds one
        set of bytes — N hosts re-reading the full checkpoint would
        multiply restore IO by the host count); the engine's load path
        broadcasts rank 0's verdict before any collective load starts."""
        if not self._cfg.verify_on_load:
            return "skipped"
        try:
            import jax

            if jax.process_count() > 1 and jax.process_index() != 0:
                return "delegated"
        except Exception:
            pass
        key = os.path.realpath(tag_dir)
        if key in self._verified_ok:
            return "ok"
        try:
            status = verify_tag_dir(tag_dir)
        except CheckpointCorruptionError as e:
            self._emit("ckpt.corrupt", tag_dir=tag_dir, error=str(e)[:300])
            raise
        if status == "ok":
            self._verified_ok.add(key)
        else:
            logger.info(f"[resilience] {tag_dir!r} has no integrity "
                        "manifest (pre-resilience checkpoint); loading "
                        "unverified")
        return status

    # -- commit: manifest + registry + retention ------------------------
    def commit(self, tag):
        from deepspeed_tpu import comm as dist

        tag = str(tag)
        out = self._inner.commit(tag)  # drains async writes / publishes
        dist.barrier()                 # every process's files are final
        if dist.get_rank() == 0:
            for root in sorted(self._roots):
                tag_dir = os.path.join(root, tag)
                if not os.path.isdir(tag_dir):
                    continue
                retry_io(lambda d=tag_dir: write_manifest(d),
                         retries=self._cfg.retries,
                         backoff_secs=self._cfg.retry_backoff_secs,
                         what=f"manifest for {tag_dir!r}",
                         on_retry=self._on_retry("commit", tag_dir))
                verified = record_verified(root, tag)
                self._emit("ckpt.verified", tag=tag, save_dir=root,
                           n_verified=len(verified))
                log_dist(f"[resilience] committed integrity manifest for "
                         f"{tag!r} ({len(verified)} verified-good tag(s) "
                         f"in {root})", ranks=[0])
                self._prune(root, verified)
        dist.barrier()                 # peers wait for manifest publish
        self._roots = set()
        return out

    def _prune(self, save_dir: str, verified: List[str]):
        """keep-last-N retention over *verified* tags only. The newest
        verified-good tag and the protected tags (``preempt``) are never
        deleted; tags this engine never published are never touched."""
        import shutil

        keep_n = int(self._cfg.keep_last_n)
        if keep_n <= 0:
            return
        protected = set(PROTECTED_TAGS)
        try:  # never strand the `latest` pointer at a deleted dir
            with open(os.path.join(save_dir, "latest")) as f:
                protected.add(f.read().strip())
        except OSError:
            pass
        deletable = [t for t in verified if t not in protected]
        victims = deletable[:-max(1, keep_n)]
        if not victims:
            return
        survivors = [t for t in verified if t not in victims]
        _write_verified(save_dir, survivors)  # registry first: a crash
        # between registry and rmtree leaves an extra dir, never a
        # registry entry pointing at a deleted checkpoint
        for t in victims:
            shutil.rmtree(os.path.join(save_dir, t), ignore_errors=True)
            self._verified_ok.discard(
                os.path.realpath(os.path.join(save_dir, t)))
        self._emit("ckpt.prune", save_dir=save_dir, pruned=victims,
                   kept=survivors)
        log_dist(f"[resilience] retention pruned {victims} "
                 f"(keep_last_n={keep_n})", ranks=[0])
