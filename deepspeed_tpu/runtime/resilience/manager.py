"""Resilience manager — the per-engine facade over the fault-tolerance
layer (same contract as ``telemetry.Telemetry``: construction is cheap,
disabled-by-default, and a disabled manager is a single bool check on
the hot path; the compiled step program is byte-identical with the
subsystem off — pinned in ``tests/unit/test_resilience.py``).

Pieces (tentpole contract, ISSUE 3):

1. **checkpoint integrity** — :meth:`wrap_checkpoint_engine` threads the
   engine's checkpoint tier through
   :class:`~deepspeed_tpu.runtime.resilience.integrity.ResilientCheckpointEngine`
   (manifest commit, verify-on-load, retry, retention);
2. **step sentinel** — NaN/Inf + loss-spike detection at every optimizer
   boundary with policy ``warn | skip | abort | rollback``
   (:mod:`~deepspeed_tpu.runtime.resilience.sentinel`); ``skip`` is
   realized in-graph (:attr:`sentinel_in_graph` forces the fp16-style
   overflow check on), so a skipped step matches an fp16 overflow skip
   bit-for-bit;
3. **hang watchdog** — background stall detector with stack dump +
   clean abort (:mod:`~deepspeed_tpu.runtime.resilience.watchdog`);
4. faults land as ``fault`` telemetry events (when telemetry is on) and
   in a local ring buffer either way — the tail the watchdog dumps.
"""

import contextlib
from collections import deque
from typing import Callable, Optional

from deepspeed_tpu.runtime.resilience.sentinel import (SentinelAbort,
                                                       StepSentinel)
from deepspeed_tpu.runtime.resilience.watchdog import HangWatchdog
from deepspeed_tpu.utils.logging import log_dist, logger


def _as_config(config):
    """Accept a parsed ResilienceConfig, a raw dict, or None."""
    if config is None:
        config = {}
    if isinstance(config, dict):
        from deepspeed_tpu.runtime.config import ResilienceConfig

        config = ResilienceConfig(**config)
    return config


def fast_forward(data_iter, n_batches: int) -> int:
    """Advance a batch iterator past ``n_batches`` MICRO-batches (the
    data-pipeline half of a rollback: the restored step counter is behind
    the stream — pass the rollback info's ``micro_batches_to_replay``).
    Returns how many batches were actually consumed."""
    consumed = 0
    sentinel = object()
    for _ in range(max(0, int(n_batches))):
        if next(data_iter, sentinel) is sentinel:
            break
        consumed += 1
    return consumed


class Resilience:
    def __init__(self, config=None, telemetry=None, name: str = "engine",
                 serving: bool = False):
        self.config = _as_config(config)
        self.enabled = bool(self.config.enabled)
        self.name = name
        self.serving = bool(serving)
        self.telemetry = telemetry
        self.fault_tail = deque(maxlen=128)
        self._closing = False
        self.sentinel: Optional[StepSentinel] = None
        self.watchdog: Optional[HangWatchdog] = None
        self.rollbacks = 0
        # user hook called after each sentinel rollback with an info dict
        # ({"restored_tag", "restored_step", "failed_step",
        #   "steps_to_replay", "micro_batches_to_replay"}) — the place to
        # fast-forward a data iterator the engine does not own:
        # fast_forward(data_iter, info["micro_batches_to_replay"])
        # (iterators yield MICRO-batches; steps_to_replay counts
        # optimizer steps, gas micro-batches each)
        self.on_rollback: Optional[Callable] = None
        self._engine = None
        self._rollback_dir = None
        if not self.enabled:
            return
        if self.config.sentinel.enabled:
            self.sentinel = StepSentinel(self.config.sentinel,
                                         on_trip=self._handle_trip)
        wd = self.config.watchdog
        if wd.enabled:
            self.watchdog = HangWatchdog(
                timeout_secs=wd.timeout_secs, poll_secs=wd.poll_secs,
                dump_dir=wd.dump_dir, abort=wd.abort,
                exit_code=wd.exit_code, name=name,
                tail_fn=self.tail, emit=self.emit_fault,
                flush=self._flush_telemetry,
                # serving: an idle gap between requests is healthy — the
                # stall timer only runs while a request is in flight
                # (training step cadence is continuous, so any gap counts)
                idle_ok=self.serving)

    # ------------------------------------------------------------------
    # fault event plumbing
    def emit_fault(self, event_name: str, step: Optional[int] = None,
                   **data):
        event = {"name": event_name, "step": step, **data}
        self.fault_tail.append(event)
        if self.telemetry is not None:
            self.telemetry.emit("fault", event_name, step=step, data=data)

    def tail(self, n: int = 50):
        """Recent events for the watchdog dump: the telemetry tail when
        telemetry is live, this manager's fault tail otherwise."""
        if self.telemetry is not None and getattr(self.telemetry, "enabled",
                                                  False):
            tele_tail = self.telemetry.tail(n)
            if tele_tail:
                return tele_tail
        return list(self.fault_tail)[-n:]

    def _flush_telemetry(self):
        if self.telemetry is not None:
            self.telemetry.flush()

    # ------------------------------------------------------------------
    # piece 1: checkpoint integrity
    def wrap_checkpoint_engine(self, inner):
        """Thread a checkpoint engine through the integrity layer (no-op
        when resilience or its checkpoint integrity is off)."""
        if not self.enabled or not self.config.checkpoint.integrity:
            return inner
        from deepspeed_tpu.runtime.resilience.integrity import (
            ResilientCheckpointEngine)

        return ResilientCheckpointEngine(inner, self.config.checkpoint,
                                         emit=self.emit_fault)

    def note_save_dir(self, save_dir: str):
        """Remember where checkpoints go (the rollback target when
        ``resilience.checkpoint.rollback_dir`` is not pinned)."""
        self._rollback_dir = save_dir

    # ------------------------------------------------------------------
    # piece 2: step sentinel
    @property
    def sentinel_in_graph(self) -> bool:
        """``policy: skip`` compiles the fp16-style grads NaN/Inf check
        into the step regardless of precision mode — the ONLY compiled-
        program change resilience can make, and only under this policy."""
        return (self.enabled and self.sentinel is not None
                and self.config.sentinel.policy == "skip")

    def _handle_trip(self, step: int, value, reason: str):
        policy = self.config.sentinel.policy
        self.emit_fault("sentinel.trip", step=step, loss=value,
                        reason=reason, policy=policy)
        if self._closing:
            # close-time drain: surface the trip loudly (event + the
            # sentinel's own warning) but never abort or roll back a
            # teardown in progress
            return
        if policy == "warn":
            return
        if policy == "skip":
            # the in-graph check already refused the update for nonfinite
            # grads; a loss *spike* has finite grads — nothing in-graph to
            # skip, so it degrades to the warn above (documented)
            return
        if policy == "abort":
            self._flush_telemetry()
            raise SentinelAbort(
                f"sentinel abort at step {step}: loss={value} ({reason}) — "
                "restart and resume from the last verified-good checkpoint")
        if policy == "rollback":
            self._rollback(step, value, reason)

    def _rollback(self, step: int, value, reason: str):
        engine = self._engine
        save_dir = self.config.checkpoint.rollback_dir or self._rollback_dir
        if engine is None or save_dir is None:
            logger.warning(
                "[resilience] sentinel policy is 'rollback' but no "
                "checkpoint directory is known (no save_checkpoint yet and "
                "no resilience.checkpoint.rollback_dir) — degrading to "
                "warn for this trip")
            self.emit_fault("sentinel.rollback_unavailable", step=step,
                            reason=reason)
            return
        self.rollbacks += 1
        limit = int(self.config.sentinel.max_rollbacks)
        if limit > 0 and self.rollbacks > limit:
            self._flush_telemetry()
            raise SentinelAbort(
                f"sentinel rolled back {self.rollbacks - 1}x already "
                f"(max_rollbacks={limit}) and tripped again at step {step} "
                f"({reason}) — the divergence is persistent; aborting")
        tag, _ = engine.load_checkpoint(save_dir)
        if tag is None:
            self._flush_telemetry()
            raise SentinelAbort(
                f"sentinel rollback at step {step} found no checkpoint in "
                f"{save_dir!r}")
        self.sentinel.reset()  # the restored trajectory starts fresh
        restored_step = engine.global_steps
        replay = max(0, step - restored_step)
        try:
            gas = int(engine.gradient_accumulation_steps())
        except Exception:
            gas = 1
        info = {"restored_tag": str(tag), "restored_step": restored_step,
                "failed_step": step,
                "steps_to_replay": replay,
                # what a batch ITERATOR must skip: the failed trajectory
                # consumed gas micro-batches per optimizer step — pass
                # THIS to fast_forward(data_iter, n)
                "micro_batches_to_replay": replay * max(1, gas)}
        self.emit_fault("sentinel.rollback", step=step, loss=value,
                        reason=reason, **info)
        log_dist(
            f"[resilience] ROLLBACK: step {step} tripped the sentinel "
            f"({reason}); restored {tag!r} at step {restored_step} — "
            f"fast-forward the data pipeline {info['steps_to_replay']} "
            f"optimizer step(s) = {info['micro_batches_to_replay']} "
            "micro-batch(es)", ranks=[0])
        if self.on_rollback is not None:
            self.on_rollback(info)
        return info

    # ------------------------------------------------------------------
    # step-boundary hook (one call per optimizer step, from the engines)
    def on_step_boundary(self, engine, step: int, loss=None):
        if not self.enabled:
            return
        self._engine = engine
        if self.watchdog is not None:
            self.watchdog.start()
            self.watchdog.notify(step)
        if self.sentinel is not None:
            self.sentinel.observe(step, loss)

    def observe_synced_loss(self, step: int, value: float):
        """Engines that already fetched the loss (``train_batch`` returns
        a float) hand it over so the sentinel never forces a second
        device sync."""
        if self.enabled and self.sentinel is not None:
            self.sentinel.observe_value(step, value)

    def drain_sentinel(self):
        """Force-check every pending lagged loss NOW. Called before a
        checkpoint save (a still-unjudged NaN boundary must not become a
        verified-good checkpoint) and at close (the final boundary's loss
        would otherwise never be judged)."""
        if self.enabled and self.sentinel is not None:
            self.sentinel.drain()

    def serving_request_begin(self):
        """Serving engines: a request entered the engine — the watchdog
        stall timer runs until the matching :meth:`serving_heartbeat`."""
        if self.enabled and self.watchdog is not None:
            self.watchdog.start()
            self.watchdog.busy_begin()

    @contextlib.contextmanager
    def watchdog_suspended(self):
        """Pause the hang watchdog for a known-long non-step phase (a
        checkpoint save to a slow blob store can legitimately outlast the
        step timeout; killing the job mid-save would abort the very write
        that makes restarts safe)."""
        wd = self.watchdog if self.enabled else None
        if wd is not None:
            wd.suspend()
        try:
            yield
        finally:
            if wd is not None:
                wd.resume()

    def serving_step_progress(self):
        """Serving engines: a decode step completed (tokens observed on
        the host) without finishing any request — refresh the stall timer
        so a server saturated with long generations is never judged hung
        between completions. Touch only: brackets and arming untouched."""
        if self.enabled and self.watchdog is not None:
            self.watchdog.touch()

    def serving_request_abandon(self):
        """A request raised before completing: clear its busy bracket so
        the idle server is not later judged hung by a leaked counter."""
        if self.enabled and self.watchdog is not None:
            self.watchdog.busy_end()

    def serving_heartbeat(self, count: int):
        """Serving engines: request completion feeds the watchdog (a hung
        generate step is a hung collective too; idle gaps between
        requests do not count as stalls)."""
        if self.enabled and self.watchdog is not None:
            self.watchdog.start()
            self.watchdog.notify(count)
            self.watchdog.busy_end()

    # ------------------------------------------------------------------
    def summary(self):
        return {
            "enabled": self.enabled,
            "sentinel_trips": list(getattr(self.sentinel, "trips", [])),
            "rollbacks": self.rollbacks,
            "watchdog_fired": bool(getattr(self.watchdog, "fired", False)),
            "faults": list(self.fault_tail),
        }

    def close(self):
        # judge any still-pending lagged losses first — loudly (event +
        # warning) but without abort/rollback side effects mid-teardown
        self._closing = True
        try:
            self.drain_sentinel()
        finally:
            self._closing = False
        if self.watchdog is not None:
            self.watchdog.stop()
        self._engine = None
