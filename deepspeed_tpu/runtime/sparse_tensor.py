"""Row-sparse tensor + sparse gradient allreduce.

Capability parity with the reference ``runtime/sparse_tensor.py:11``
(``SparseTensor``, an IndexedSlices-style row-compressed wrapper) and the
engine's sparse embedding-grad allreduce (``engine.py:2459-2541``): an
embedding gradient touches at most batch×seq rows of a [vocab, d] table, so
exchanging (row_indices, row_values) instead of the dense table cuts
traffic by vocab/(B·T).

TPU placement note: inside one compiled step GSPMD already reduces
embedding grads as part of the sharded program (dense psum over ICI — the
compiler overlaps it and the rows are needed dense for the optimizer
update anyway). The row-compressed path pays off at the HOST boundaries —
the optimizer-offload tier's device→host grad transfer and any DCN-side
aggregation — which is exactly where this module plugs in.
"""

from typing import Optional, Sequence

import numpy as np

from deepspeed_tpu import comm as dist


class SparseTensor:
    """Row-compressed view of a 2-D (or leading-dim-indexed) array."""

    def __init__(self, dense=None, indices=None, values=None,
                 dense_size: Optional[Sequence[int]] = None):
        if dense is not None:
            dense = np.asarray(dense)
            nz = np.abs(dense).reshape(dense.shape[0], -1).sum(axis=1)
            self.indices = np.nonzero(nz)[0].astype(np.int64)
            self.values = np.ascontiguousarray(dense[self.indices])
            self.dense_size = tuple(dense.shape)
        else:
            self.indices = (np.asarray(indices, np.int64)
                            if indices is not None else None)
            self.values = np.asarray(values) if values is not None else None
            self.dense_size = tuple(dense_size) if dense_size else None

    @staticmethod
    def type() -> str:
        return "deepspeed.SparseTensor"  # reference type tag

    @property
    def nnz_rows(self) -> int:
        return 0 if self.indices is None else len(self.indices)

    def density(self) -> float:
        if not self.dense_size:
            return 1.0
        return self.nnz_rows / max(1, self.dense_size[0])

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.dense_size, self.values.dtype)
        # duplicate indices accumulate (coalesce semantics)
        np.add.at(out, self.indices, self.values)
        return out

    def coalesce(self) -> "SparseTensor":
        uniq, inv = np.unique(self.indices, return_inverse=True)
        vals = np.zeros((len(uniq),) + self.values.shape[1:],
                        self.values.dtype)
        np.add.at(vals, inv, self.values)
        return SparseTensor(indices=uniq, values=vals,
                            dense_size=self.dense_size)

    def sparse_size(self):
        """(compressed elements, dense elements) — reference diagnostic."""
        dense_n = int(np.prod(self.dense_size))
        comp = self.nnz_rows + (0 if self.values is None else self.values.size)
        return comp, dense_n


def should_use_sparse(dense_grad, threshold: float = 0.5) -> bool:
    """Worth compressing? (row density below ``threshold``)."""
    dense_grad = np.asarray(dense_grad)
    if dense_grad.ndim < 2:
        return False
    nz = np.abs(dense_grad).reshape(dense_grad.shape[0], -1).sum(axis=1)
    return (np.count_nonzero(nz) / dense_grad.shape[0]) < threshold


def sparse_all_reduce(st: SparseTensor, average: bool = True) -> SparseTensor:
    """Allreduce of a row-sparse gradient across processes (host regime).

    Mirrors the reference ``sparse_allreduce`` (``engine.py:2494``):
    all-gather (indices, values) from every rank, concatenate, coalesce.
    Single-process (the usual single-controller TPU case) this is a
    coalesce; multi-host it rides ``comm``'s host-regime collectives.
    """
    import jax

    # host-regime exchange: the unit of participation is the PROCESS (each
    # host holds its local grad), not the device — cf. comm.get_rank docs
    world = jax.process_count()
    if world > 1:
        # ranks hold different nnz: agree on the max, pad with sentinel
        # rows, fixed-size all-gather, drop sentinels (the reference pads
        # its sparse allreduce the same way, engine.py:2520)
        max_nnz = int(np.asarray(dist.all_reduce(
            np.asarray([st.nnz_rows], np.int64), op=dist.ReduceOp.MAX))[0])
        pad = max_nnz - st.nnz_rows
        idx_p = np.pad(st.indices, (0, pad), constant_values=-1)
        tail = st.values.shape[1:]
        val_p = np.pad(st.values.reshape(st.nnz_rows, -1),
                       ((0, pad), (0, 0)))
        all_idx = np.asarray(dist.all_gather(idx_p)).reshape(-1)
        all_val = np.asarray(dist.all_gather(val_p)).reshape(
            world * max_nnz, -1)
        keep = all_idx >= 0
        st = SparseTensor(
            indices=all_idx[keep],
            values=all_val[keep].reshape((-1,) + tail),
            dense_size=st.dense_size)
    out = st.coalesce()
    if average and world > 1:
        out.values = out.values / world
    return out
