"""Typed-config base class with deprecated-field migration.

Capability parity with the reference ``deepspeed/runtime/config_utils.py``
(``DeepSpeedConfigModel`` + ``Field(deprecated=True, new_param=...)``
machinery), written against pydantic v2.

Deprecated fields are declared via ``json_schema_extra``::

    my_old_field: int = Field(0, json_schema_extra={
        "deprecated": True,
        "new_param": "my_new_field",   # dotted path OK
        "new_param_fn": lambda x: x,   # value translation
    })
"""

import json
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, model_validator

from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all config sub-models: unknown keys rejected, deprecation handled."""

    model_config = ConfigDict(
        extra="forbid",
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        arbitrary_types_allowed=True,
    )

    def __init__(self, strict=False, **data):
        if not strict:  # "auto" values fall back to field defaults (reference behavior)
            data = {k: v for k, v in data.items() if (v != "auto" or k == "replace_method")}
        super().__init__(**data)

    @model_validator(mode="after")
    def _migrate_deprecated(self):
        fields = type(self).model_fields
        for name, field in fields.items():
            extra = field.json_schema_extra or {}
            if not isinstance(extra, dict) or not extra.get("deprecated"):
                continue
            if name not in (self.model_fields_set or ()):
                continue
            new_param = extra.get("new_param", "")
            logger.warning(
                f"Config parameter {name} is deprecated"
                + (f" use {new_param} instead" if new_param else "")
            )
            if new_param and extra.get("set_new_param", True):
                # Don't overwrite an explicitly-set new param.
                fn = extra.get("new_param_fn", lambda x: x)
                value = fn(getattr(self, name))
                parts = new_param.split(".")
                target = self
                for p in parts[:-1]:
                    target = getattr(target, p)
                if parts[-1] not in (target.model_fields_set or ()):
                    # object.__setattr__: plain setattr would re-enter this
                    # validator via validate_assignment.
                    object.__setattr__(target, parts[-1], value)
        return self

    def get(self, key, default=None):
        return getattr(self, key, default)

    def __getitem__(self, key):
        return getattr(self, key)


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value: Any):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict, param_name: str, param_default_value: Any):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value: Any):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load hook rejecting duplicate keys (reference ``config_utils.py``)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ScientificNotationEncoder(json.JSONEncoder):
    """Display large/small floats in scientific notation in config dumps
    (reference ``config_utils.py`` encoder of the same name)."""

    def iterencode(self, o, _one_shot=False, level=0):
        indent = self.indent if self.indent is not None else 4
        prefix_close = " " * level * indent
        level += 1
        prefix = " " * level * indent
        if isinstance(o, bool):
            return "true" if o else "false"
        elif isinstance(o, float) and (o > 1e3 or o < 1e-3):
            return f"{o:e}"
        elif isinstance(o, dict):
            x = [f"\n{prefix}\"{k}\": {self.iterencode(v, level=level)}" for k, v in o.items()]
            return "{" + ", ".join(x) + f"\n{prefix_close}" + "}"
        elif isinstance(o, list):
            x = [self.iterencode(el, level=level) for el in o]
            return "[" + ", ".join(x) + "]"
        else:
            return ",".join(super().iterencode(o, _one_shot))
