"""MoQ — mixed-precision (quantize-aware) training scheduler.

Capability parity with the reference ``runtime/quantize.py:9``
(``Quantizer``: per-step precision schedule driving fake-quantization,
optionally eigenvalue-adaptive — ``factor = 1 + floor(eigenvalue * 4)``
stretches a layer's ``quantize_period`` so high-curvature layers lose
precision more slowly; engine hookup ``_configure_quantization``,
``engine.py:1400``).

TPU-native shape: instead of mutating module weights in hooks, the
schedule compiles into the engine's existing QAT transform
(``compression.Compressor``) as a stack of step-gated fake-quant plans —
one per bit-width transition, each gated by ``global_step >= offset``
inside the jitted step (no retrace per step; one recompile only when
eigenvalues re-scale the schedule).
"""

import math
from typing import Dict, List, Optional

from deepspeed_tpu.compression.constants import WEIGHT_QUANTIZATION
from deepspeed_tpu.utils.logging import logger


class MoQSchedule:
    """Precision trajectory: ``start_bits`` → ``target_bits``, one bit per
    ``period`` steps after ``offset`` (the reference halves precision at
    period boundaries and doubles the period each transition)."""

    def __init__(self, start_bits: int = 16, target_bits: int = 8,
                 period: int = 100, offset: int = 0,
                 period_doubling: bool = True):
        if target_bits > start_bits:
            raise ValueError("target_bits must be <= start_bits")
        self.start_bits = int(start_bits)
        self.target_bits = int(target_bits)
        self.period = int(period)
        self.offset = int(offset)
        self.period_doubling = period_doubling

    def transitions(self, period_factor: float = 1.0) -> List[Dict]:
        """[(step_offset, bits)] — the first entry applies ``start_bits``
        AT the offset (so start==target is fixed-bits QAT, not a no-op);
        later entries drop one bit per (stretched, doubling) period."""
        out = [{"offset": self.offset, "bits": self.start_bits}]
        step = self.offset
        period = max(1, int(round(self.period * period_factor)))
        for bits in range(self.start_bits - 1, self.target_bits - 1, -1):
            step += period
            out.append({"offset": step, "bits": bits})
            if self.period_doubling:
                period *= 2
        return out


class MoQQuantizer:
    """Builds/refreshes Compressor plans for the MoQ schedule.

    ``eigenvalues``: optional ``{block_path_prefix: eigenvalue}`` (the
    engine's ``Eigenvalue.compute_eigenvalue`` output, normalized to max 1)
    — a block's period is stretched by ``1 + floor(eig * 4)``.
    """

    def __init__(self, schedule: MoQSchedule, groups: int = 1,
                 symmetric: bool = True,
                 match_patterns: Optional[List[str]] = None):
        self.schedule = schedule
        self.groups = int(groups)
        self.symmetric = symmetric
        # None = every >=2-D weight (the reference's TWO_D_PARAMS rule);
        # a list restricts to named leaf segments
        self.match_patterns = match_patterns
        self.eigenvalues: Dict[str, float] = {}

    def set_eigenvalues(self, eigenvalues: Dict[str, float]):
        """Normalize to [0, 1] like the reference (it divides by the max
        layer eigenvalue before computing the factor)."""
        if not eigenvalues:
            return
        mx = max(abs(v) for v in eigenvalues.values()) or 1.0
        self.eigenvalues = {k: abs(v) / mx for k, v in eigenvalues.items()}

    def _factor_for(self, path: str) -> float:
        # eigenvalue keys are whole top-level blocks: match the path's
        # FIRST SEGMENT exactly (prefix matching would let "dense" claim
        # "dense2/kernel")
        head = path.split("/", 1)[0]
        eig = self.eigenvalues.get(head)
        return 1.0 + math.floor(eig * 4) if eig is not None else 1.0

    def build_plans(self, params_abstract) -> Dict[str, List[Dict]]:
        """Compressor-style plans: one fake-quant entry per bit transition,
        later (lower-bit) entries overriding earlier ones via the
        Compressor's sequential jnp.where gating."""
        import jax

        from deepspeed_tpu.utils.pytree import flatten_with_path_strings

        flat, _ = flatten_with_path_strings(params_abstract)
        plans: Dict[str, List[Dict]] = {}
        for path, leaf in flat:
            if getattr(leaf, "ndim", 0) < 2:
                continue  # the reference quantizes >=2-D weights only
            if self.match_patterns is not None:
                leafname = path.rsplit("/", 1)[-1]
                if leafname not in self.match_patterns:
                    continue
            factor = self._factor_for(path)
            entries = []
            for tr in self.schedule.transitions(factor):
                entries.append({
                    "technique": WEIGHT_QUANTIZATION,
                    "params": {"bits": tr["bits"], "groups": self.groups,
                               "symmetric": self.symmetric},
                    "schedule_offset": tr["offset"],
                })
            if entries:
                plans[path] = entries
        if self.eigenvalues:
            logger.info(
                f"MoQ: eigenvalue-adaptive schedule over {len(plans)} "
                f"weights (factors up to "
                f"{max(self._factor_for(p) for p in plans):.0f}x)")
        return plans
