"""aio-backed pipelined NVMe swapper for layer-streamed training.

Capability parity with the reference swap-tensor engines:
``runtime/swap_tensor/partitioned_param_swapper.py:35`` (async param
swap with pinned staging buffers), ``partitioned_optimizer_swapper.py:27``
(optimizer-state swap around the CPU-Adam update) and
``pipelined_optimizer_swapper.py:55`` (read layer ``l+1`` / write layer
``l-1`` while layer ``l`` updates).

The round-2 NVMe tier was ``np.memmap``: synchronous page-fault reads in
the middle of the H2D stream and unbounded dirty-page writeback. This
module replaces it with explicit I/O on the C++ aio op
(``csrc/aio/ds_aio.cpp``): per-kind flat files holding all layers at a
4 KiB-aligned stride, a bounded pool of aligned host buffers (the pinned
staging buffers of the reference), ``async_pread`` prefetch ahead of the
compute stream, and ``async_pwrite`` writeback behind the optimizer
sweep. Host RAM is bounded by ``num_buffers`` layer-strides per kind —
never the whole parameter file.

Layout: the scanned block pytree (every leaf ``[L, ...]``) flattens to a
fixed leaf order; one layer's leaves concatenate into a flat fp32 record
of ``layer_nbytes``, padded to the 4 KiB stride O_DIRECT wants.
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle
from deepspeed_tpu.utils.pytree import flatten_with_path_strings

_ALIGN = 4096


class LayerSpec:
    """Fixed flat layout of one layer's leaves inside a stride record."""

    def __init__(self, blocks_tree: Any):
        import jax

        flat, self.treedef = flatten_with_path_strings(blocks_tree)
        self.paths: List[str] = [p for p, _ in flat]
        leaves = [np.asarray(v) for _, v in flat]
        L = leaves[0].shape[0]
        assert all(a.shape[0] == L for a in leaves), (
            "scanned block leaves must share the leading layer axis")
        self.n_layers = int(L)
        self.shapes: List[Tuple[int, ...]] = [a.shape[1:] for a in leaves]
        self.sizes: List[int] = [int(np.prod(s)) for s in self.shapes]
        self.offsets: List[int] = list(np.cumsum([0] + self.sizes[:-1]))
        self.layer_size = int(sum(self.sizes))          # fp32 elements
        self.layer_nbytes = self.layer_size * 4
        self.stride = -(-self.layer_nbytes // _ALIGN) * _ALIGN

    def views(self, buf: np.ndarray) -> Any:
        """Pytree of leaf views into a flat fp32 buffer (no copies)."""
        import jax

        flat32 = buf.view(np.float32)
        leaves = [flat32[o:o + n].reshape(s) for o, n, s in
                  zip(self.offsets, self.sizes, self.shapes)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def pack(self, layer_tree: Any, out: np.ndarray) -> None:
        import jax

        flat, _ = flatten_with_path_strings(layer_tree)
        flat32 = out.view(np.float32)
        for (path, leaf), o, n in zip(flat, self.offsets, self.sizes):
            flat32[o:o + n] = np.asarray(leaf, np.float32).reshape(-1)


class LayerFileStore:
    """One on-disk file of ``n_layers`` stride records + a bounded pool of
    aligned staging buffers with async read/write through the aio op.

    Each buffer slot owns its own aio handle: the C++ ``wait`` drains a
    whole handle, so per-slot handles are what make the waits *per-layer*
    — ``get(l)`` waits only for ``l``'s read, never for the prefetch of
    ``l+1`` issued moments earlier, and ``flush_writes`` only for slots
    that actually have a write in flight.
    """

    _READING, _RESIDENT, _WRITING = "reading", "resident", "writing"

    def __init__(self, filename: str, spec: LayerSpec,
                 num_buffers: int = 3, aio: Optional[AsyncIOHandle] = None):
        self.filename = filename
        self.spec = spec
        self._sync = aio or AsyncIOHandle(num_threads=2)  # bulk init/export
        self._handles = [AsyncIOHandle(num_threads=1)
                         for _ in range(num_buffers)]
        self._buffers = [AsyncIOHandle.aligned_array(spec.stride)
                         for _ in range(num_buffers)]
        self._free: List[int] = list(range(num_buffers))
        self._slot_of: Dict[int, int] = {}   # layer -> slot
        self._state: Dict[int, str] = {}     # slot -> reading|resident|writing

    # -- bulk init / export -------------------------------------------
    def write_all(self, blocks_tree: Any) -> None:
        """Synchronously persist a full ``[L, ...]`` tree (startup/restore)."""
        import jax

        spec = self.spec
        # preallocate the file so positional writes are stable
        with open(self.filename, "wb") as f:
            f.truncate(spec.stride * spec.n_layers)
        buf = AsyncIOHandle.aligned_array(spec.stride)
        for l in range(spec.n_layers):
            row = jax.tree_util.tree_map(lambda a: np.asarray(a)[l],
                                         blocks_tree)
            spec.pack(row, buf)
            self._sync.sync_pwrite(buf, self.filename, l * spec.stride)

    def read_layer_copy(self, l: int) -> Any:
        """One layer as fresh RAM arrays (checkpoint export path)."""
        import jax

        buf = AsyncIOHandle.aligned_array(self.spec.stride)
        self._sync.sync_pread(buf, self.filename, l * self.spec.stride)
        return jax.tree_util.tree_map(np.array, self.spec.views(buf))

    # -- streamed access ----------------------------------------------
    def prefetch(self, l: int) -> None:
        """Issue an async read of layer ``l`` if not already resident or
        in flight. Requires a free buffer (callers release as they go)."""
        if l in self._slot_of or not (0 <= l < self.spec.n_layers):
            return
        if not self._free:
            raise RuntimeError(
                "LayerFileStore: no free staging buffer for prefetch — "
                "release() layers as the stream advances")
        slot = self._free.pop()
        self._handles[slot].async_pread(self._buffers[slot], self.filename,
                                        l * self.spec.stride)
        self._slot_of[l] = slot
        self._state[slot] = self._READING

    def get(self, l: int) -> Any:
        """Layer ``l`` as a pytree of buffer views, waiting only for ``l``'s
        own read (cold miss issues one)."""
        if l not in self._slot_of:
            self.prefetch(l)
        slot = self._slot_of[l]
        if self._state[slot] == self._READING:
            self._handles[slot].wait()
            self._state[slot] = self._RESIDENT
        return self.spec.views(self._buffers[slot])

    def flat(self, l: int) -> np.ndarray:
        """Layer ``l``'s resident record as a flat fp32 view (the raw
        operand the pipelined Adam kernel updates in place)."""
        slot = self._slot_of[l]
        assert self._state[slot] == self._RESIDENT, self._state[slot]
        return self._buffers[slot].view(np.float32)[:self.spec.layer_size]

    def release(self, l: int) -> None:
        slot = self._slot_of.pop(l, None)
        if slot is not None:
            if self._state[slot] == self._WRITING:
                self._handles[slot].wait()
            del self._state[slot]
            self._free.append(slot)

    def write_back(self, l: int) -> None:
        """Async write of layer ``l``'s (mutated) resident buffer; the
        buffer stays owned by the layer until ``flush_writes``+``release``."""
        slot = self._slot_of[l]
        self._handles[slot].async_pwrite(self._buffers[slot], self.filename,
                                         l * self.spec.stride)
        self._state[slot] = self._WRITING

    def flush_writes(self) -> None:
        for slot, state in self._state.items():
            if state == self._WRITING:
                self._handles[slot].wait()
                self._state[slot] = self._RESIDENT

    @property
    def _resident(self) -> Dict[int, int]:
        """layer -> slot for resident/writing layers (introspection only)."""
        return {l: s for l, s in self._slot_of.items()
                if self._state[s] != self._READING}

    @property
    def _reading(self) -> Dict[int, int]:
        return {l: s for l, s in self._slot_of.items()
                if self._state[s] == self._READING}

    @property
    def _writes_pending(self) -> int:
        return sum(1 for s in self._state.values() if s == self._WRITING)

    def reset(self) -> None:
        """Drop residency (e.g. after an external restore rewrote the file)."""
        for slot, state in list(self._state.items()):
            if state in (self._READING, self._WRITING):
                self._handles[slot].wait()
        self._slot_of.clear()
        self._state.clear()
        self._free = list(range(len(self._buffers)))


class PipelinedOptimizerSwapper:
    """Layer-pipelined CPU-Adam over NVMe-resident masters and moments
    (reference ``pipelined_optimizer_swapper.py:55``).

    Per layer ``l``: (param, m, v) records stream in ahead of the update,
    the native ``ds_adam_step`` kernel runs on the staging buffers, and
    the mutated records stream back out while layer ``l+1`` updates.
    Host RAM: ``num_buffers`` strides per store — independent of depth.
    """

    def __init__(self, nvme_path: str, blocks_tree: Any,
                 lr: float, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 num_buffers: int = 3):
        from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam

        os.makedirs(nvme_path, exist_ok=True)
        self.spec = LayerSpec(blocks_tree)
        self.stores = {
            kind: LayerFileStore(
                os.path.join(nvme_path, f"blocks.{kind}.bin"), self.spec,
                num_buffers=num_buffers)
            for kind in ("param", "exp_avg", "exp_avg_sq")}
        # a private kernel instance provides the opt_id + hyperparams; its
        # per-name state dict stays empty (slices come from the stores)
        self._adam = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps,
                                      weight_decay=weight_decay,
                                      adamw_mode=adamw_mode)
        self.step_count = 0

        import jax

        zeros = jax.tree_util.tree_map(
            lambda a: np.zeros_like(np.asarray(a), dtype=np.float32),
            blocks_tree)
        self.stores["param"].write_all(blocks_tree)
        self.stores["exp_avg"].write_all(zeros)
        self.stores["exp_avg_sq"].write_all(zeros)

    @property
    def n_layers(self) -> int:
        return self.spec.n_layers

    # -- param streaming for the forward/backward compute stream -------
    def prefetch_params(self, l: int) -> None:
        self.stores["param"].prefetch(l)

    def get_params(self, l: int) -> Any:
        return self.stores["param"].get(l)

    def release_params(self, l: int) -> None:
        self.stores["param"].release(l)

    # -- the pipelined update sweep ------------------------------------
    def step(self, grads_blocks: Any, lr: float,
             grad_scale: float = 1.0, clip_coef: float = 1.0) -> None:
        """One Adam step over every layer.

        ``grads_blocks``: ``[L, ...]`` fp32 grad tree (RAM-resident — the
        accumulation buffer the backward stream fills). ``grad_scale``
        multiplies grads (1/gas); ``clip_coef`` applies global-norm
        clipping decided by the caller (the global norm needs every
        layer's grads, which the caller already holds).
        """
        import ctypes
        import jax

        if lr != self._adam.lr:
            self._adam.set_lr(lr)
        self.step_count += 1
        p_store = self.stores["param"]
        m_store = self.stores["exp_avg"]
        v_store = self.stores["exp_avg_sq"]
        L = self.spec.n_layers
        scale = float(grad_scale) * float(clip_coef)
        lib = self._adam._lib
        grad_buf = np.empty(self.spec.layer_size, np.float32)

        stores = (p_store, m_store, v_store)
        for st in stores:
            st.prefetch(0)
        for l in range(L):
            if l + 1 < L:
                # read of l+1 overlaps this layer's kernel (per-slot waits:
                # get(l) below never drains these just-issued reads)
                for st in stores:
                    st.prefetch(l + 1)
            for st in stores:
                st.get(l)  # wait for l's own read (per-slot)
            pbuf, mbuf, vbuf = (st.flat(l) for st in stores)
            row = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[l], grads_blocks)
            self.spec.pack(row, grad_buf.view(np.uint8))
            if scale != 1.0:
                grad_buf *= scale
            fptr = ctypes.POINTER(ctypes.c_float)
            rc = lib.ds_adam_step(
                self._adam.opt_id, self.step_count, self.spec.layer_size,
                pbuf.ctypes.data_as(fptr),
                grad_buf.ctypes.data_as(fptr),
                mbuf.ctypes.data_as(fptr),
                vbuf.ctypes.data_as(fptr))
            if rc != 0:
                raise RuntimeError(f"pipelined cpu_adam failed at layer {l}")
            if l > 0:
                # l-1's writes flew during this layer's kernel; drain them
                # BEFORE issuing l's writes so the wait never touches l,
                # then free the slots for the l+2 prefetch next iteration
                for st in stores:
                    st.flush_writes()
                    st.release(l - 1)
            for st in stores:
                st.write_back(l)  # overlaps layer l+1's kernel
        for st in stores:
            st.flush_writes()
            st.release(L - 1)

    # -- checkpoint surface -------------------------------------------
    def read_full(self, kind: str) -> Any:
        """Assemble the full ``[L, ...]`` tree from disk (checkpoint
        export; transiently allocates the full tree in RAM)."""
        import jax

        rows = [self.stores[kind].read_layer_copy(l)
                for l in range(self.spec.n_layers)]
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *rows)

    def write_full(self, kind: str, tree: Any) -> None:
        self.stores[kind].write_all(tree)
        self.stores[kind].reset()
