"""ZeRO configuration.

Capability parity with the reference ``deepspeed/runtime/zero/config.py``
(``DeepSpeedZeroConfig``) and ``offload_config.py``. On TPU the stages map to
GSPMD sharding policies over the ``data`` mesh axis rather than explicit
partition bookkeeping:

- stage 1: optimizer state sharded over ``data`` ("weight-update sharding")
- stage 2: + gradients reduce-scattered over ``data``
- stage 3: + parameters sharded over ``data`` (gather-per-use by XLA)

Bucket sizes / overlap knobs are accepted for config compatibility; where XLA
already performs the optimization (e.g. comm/compute overlap via the
latency-hiding scheduler) they are recorded but have no direct effect.
"""

from enum import Enum
from typing import Optional

from pydantic import Field, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """Parameter offload (ZeRO-3): reference ``offload_config.py:19``."""

    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(100_000_000, ge=0)
    max_in_cpu: int = Field(1_000_000_000, ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """Optimizer offload (ZeRO-1/2/3): reference ``offload_config.py:50``."""

    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """``zero_optimization`` section: reference ``runtime/zero/config.py:76``."""

    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = Field(1_000_000_000, ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param", "set_new_param": False}
    )
    cpu_offload_use_pin_memory: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "set_new_param": False}
    )
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer", "set_new_param": False}
    )
    prefetch_bucket_size: int = Field(50_000_000, ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(100_000, ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(2**63 - 1, ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(1_000_000_000, ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(1_000_000_000, ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")
    stage3_gather_fp16_weights_on_model_save: bool = Field(
        False, json_schema_extra={"deprecated": True, "new_param": "gather_16bit_weights_on_model_save"}
    )
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    # ZeRO++ hpZ (arXiv:2306.10209): keep a secondary copy of the ZeRO-3
    # parameter shards inside each data replica so the per-use param
    # all-gather runs over the (small, fast) fsdp axis instead of the full
    # data x fsdp group. Opt-in; ignored (with a warning) unless the mesh
    # has an fsdp axis of size > 1 at stage 3.
    hierarchical_gather: bool = False

    @model_validator(mode="after")
    def _legacy_offload_flags(self):
        # object.__setattr__: plain assignment would re-run validators
        # (validate_assignment) and double-log deprecation warnings.
        if self.cpu_offload is True and self.offload_optimizer is None:
            object.__setattr__(self, "offload_optimizer",
                               DeepSpeedZeroOffloadOptimizerConfig(device="cpu"))
        if self.cpu_offload_param is True and self.offload_param is None:
            object.__setattr__(self, "offload_param",
                               DeepSpeedZeroOffloadParamConfig(device="cpu"))
        return self
