"""Generic tiled linears for weights that exceed the device budget.

Capability parity with the reference ``TiledLinear``
(``runtime/zero/tiling.py:27``), which splits ANY linear into
``in_splits x out_splits`` sub-linears so ZeRO-3 never materializes the
whole weight and remat boundaries stay tile-sized. Two TPU-native forms:

- :class:`TiledLinear` — the host-streaming form (ZeRO-Infinity tier):
  the fp32 ``[In, Out]`` weight stays HOST-resident and streams through
  the chip in ``[In, Ot]`` out-dim tiles, double-buffered so tile
  ``j+1``'s H2D transfer overlaps tile ``j``'s matmul. Peak device bytes
  are ``O(B*In + B*Out + 2*In*Ot)`` regardless of Out. The backward
  streams the same tiles again (weight remat): ``dx`` accumulates on
  device, per-tile ``dW`` lands in a host fp32 accumulator. Same design
  as the vocab-tiled head (``tiled_head.py``) with the online-softmax
  specifics stripped — this one serves ANY oversized linear (the
  176B-class MLP matrices, VERDICT r3 missing #3).

- :class:`TiledDense` — the in-graph form (ZeRO-3, no offload): a flax
  module storing the kernel as ``[tiles, In, Out/tiles]`` and applying
  it under ``lax.scan`` with a per-tile ``jax.checkpoint``. Under ZeRO-3
  sharding the scan gathers ONE tile per step instead of the whole
  kernel — the reference's motivation for tiling (bounding allgather
  granularity) expressed as a scan layout, exactly like the model
  stacks' scan-over-layers trick one level down.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class TiledLinear:
    """Host-resident ``[In, Out]`` linear streamed in out-dim tiles."""

    def __init__(self, in_features: int, out_features: int, out_tile: int,
                 dtype=jnp.float32, use_bias: bool = True):
        self.In = int(in_features)
        self.Out = int(out_features)
        self.Ot = max(128, min(int(out_tile), self.Out))
        self.use_bias = use_bias
        # wire dtype for H2D traffic (tiled_head.py rationale: ship tiles
        # at compute precision, not fp32)
        self.dtype = np.dtype(dtype) if dtype != jnp.bfloat16 else \
            jnp.bfloat16.dtype
        self.n_tiles = -(-self.Out // self.Ot)
        self._jit_fwd = jax.jit(self._fwd_tile, donate_argnums=(3,))
        self._jit_bwd = jax.jit(self._bwd_tile)
        self._jit_bwd_dx = jax.jit(
            lambda w, dyt: jnp.einsum("...o,io->...i", dyt,
                                      w.astype(jnp.float32)))

    # -- per-tile kernels (tile shape static; remainder tile compiles its
    #    own variant instead of padding) --------------------------------
    @staticmethod
    def _fwd_tile(x, w, b, y, lo):
        """y[..., lo:lo+Ot] = x @ w (+ b) for one weight tile."""
        yt = jnp.einsum("...i,io->...o", x, w.astype(x.dtype))
        if b is not None:
            yt = yt + b.astype(x.dtype)
        return jax.lax.dynamic_update_slice_in_dim(y, yt, lo, axis=-1)

    @staticmethod
    def _bwd_tile(x, w, dyt):
        """One tile's backward: dx-partial (device), dW and db (→ host).
        db reduces over the token axes ON DEVICE — only [Ot] crosses
        D2H, not the [B, T, Ot] gradient tile."""
        dx = jnp.einsum("...o,io->...i", dyt, w.astype(jnp.float32))
        dw = jnp.einsum("...i,...o->io", x.astype(jnp.float32),
                        dyt.astype(jnp.float32))
        db = jnp.sum(dyt.astype(jnp.float32),
                     axis=tuple(range(dyt.ndim - 1)))
        return dx, dw, db

    def _stream_tiles(self, w_host, device):
        """Double-buffered out-dim tile stream (tiled_head.py pattern)."""
        def put(j):
            lo = j * self.Ot
            hi = min(lo + self.Ot, self.Out)
            return lo, jax.device_put(
                np.asarray(w_host[:, lo:hi]).astype(self.dtype), device)

        nxt = put(0)
        for j in range(self.n_tiles):
            cur, nxt = nxt, (put(j + 1) if j + 1 < self.n_tiles else None)
            yield cur

    # -- forward --------------------------------------------------------
    def forward(self, x, w_host, b_host=None, device=None):
        """``x @ W + b`` with W streamed from host; returns device ``y``
        (``[..., Out]``, x.dtype)."""
        device = device or jax.devices()[0]
        y = jnp.zeros((*x.shape[:-1], self.Out), x.dtype)
        for lo, w_dev in self._stream_tiles(w_host, device):
            b_dev = None
            if self.use_bias and b_host is not None:
                b_dev = jax.device_put(
                    np.asarray(b_host[lo:lo + w_dev.shape[1]]).astype(
                        self.dtype), device)
            y = self._jit_fwd(x, w_dev, b_dev, y, lo)
        return y

    # -- backward -------------------------------------------------------
    def grads(self, x, w_host, dy, gw_host, gb_host=None, device=None):
        """Streaming VJP: returns device ``dx``; per-tile ``dW`` (and
        ``db``) accumulate into the host fp32 buffers in place. The
        weight is re-streamed (tile remat) — nothing tile-sized survives
        the forward."""
        device = device or jax.devices()[0]
        # fp32 accumulator: a bf16 running sum over n_tiles would feed
        # ~n_tiles * 2^-9 relative rounding into the whole backward
        dx = jnp.zeros(x.shape, jnp.float32)
        # D2H overlap: tile j's dW/db copy to host asynchronously while
        # tile j+1's matmul runs; the host accumulate is deferred one
        # iteration (same pattern as the infinity backward stream)
        # frozen-weight path (both accumulators omitted): only dx is
        # needed — skip the dW einsum and its full-matrix D2H entirely
        frozen = gw_host is None and gb_host is None
        pending = None
        for lo, w_dev in self._stream_tiles(w_host, device):
            hi = lo + w_dev.shape[1]
            dyt = jax.lax.dynamic_slice_in_dim(dy, lo, hi - lo, axis=-1)
            if frozen:
                dx = dx + self._jit_bwd_dx(w_dev, dyt)
                continue
            dx_j, dw, db = self._jit_bwd(x, w_dev, dyt)
            dx = dx + dx_j
            dw.copy_to_host_async()
            db.copy_to_host_async()
            if pending is not None:
                self._accum_tile(pending, gw_host, gb_host)
            pending = (lo, hi, dw, db)
        if pending is not None:
            self._accum_tile(pending, gw_host, gb_host)
        return dx.astype(x.dtype)

    @staticmethod
    def _accum_tile(p, gw_host, gb_host):
        lo, hi, dw, db = p
        if gw_host is not None:
            gw_host[:, lo:hi] += np.asarray(jax.device_get(dw), np.float32)
        if gb_host is not None:
            gb_host[lo:hi] += np.asarray(jax.device_get(db), np.float32)

    # -- autodiff entry -------------------------------------------------
    def __call__(self, x, w_host, b_host=None, *, gw_host=None,
                 gb_host=None, device=None):
        """Differentiable application: ``jax.grad`` flows ``dx`` through
        the streamed linear via ``jax.custom_vjp``.

        Host-accumulator contract: the WEIGHT gradient never exists as a
        device value — during backward each tile's ``dW``/``db`` adds into
        the caller's host fp32 buffers ``gw_host``/``gb_host`` in place
        (omit them to discard weight grads, e.g. frozen weights). This is
        the same side-channel the Infinity tier consumes via
        :meth:`grads`.

        Eager-only by design: under ``jit``/``grad``-of-``jit`` tracing
        there is no host to stream from — every tile would bake into the
        compiled program as a constant, materializing exactly the full
        weight this class exists to avoid — so a traced ``x`` is refused.
        The engine's host-orchestrated regime (the only place a
        host-resident weight makes sense) runs its layer loop outside jit
        anyway.
        """
        def _refuse_traced(x):
            # custom_vjp delivers CONCRETE arrays here under eager
            # jax.grad; only jit tracing leaks a tracer through
            if isinstance(x, jax.core.Tracer):
                raise TypeError(
                    "TiledLinear streams a HOST-resident weight and cannot "
                    "run under jit tracing (each tile would bake into the "
                    "compiled program as a constant — the full-weight "
                    "materialization tiling prevents). Call it outside "
                    "jit; jax.grad works eagerly.")

        @jax.custom_vjp
        def apply(x):
            _refuse_traced(x)
            return self.forward(x, w_host, b_host, device=device)

        def fwd(x):
            _refuse_traced(x)
            return self.forward(x, w_host, b_host, device=device), x

        def bwd(x_res, dy):
            return (self.grads(x_res, w_host, dy, gw_host,
                               gb_host if self.use_bias else None,
                               device=device),)

        apply.defvjp(fwd, bwd)
        return apply(x)


def tiled_dense(x, kernel, bias=None, *, precision=None):
    """Apply a ``[tiles, In, Ot]`` tiled kernel under ``lax.scan`` with a
    per-tile checkpoint: under ZeRO-3 sharding each scan step gathers one
    tile; backward regathers and recomputes per tile."""
    @jax.checkpoint
    def tile_body(carry, wb):
        w, b = wb
        yt = jnp.einsum("...i,io->...o", x, w.astype(x.dtype),
                        precision=precision)
        if b is not None:
            yt = yt + b.astype(x.dtype)
        return carry, yt

    _, y_tiles = jax.lax.scan(tile_body, 0, (kernel, bias))
    # [tiles, ..., Ot] -> [..., tiles*Ot]
    y = jnp.moveaxis(y_tiles, 0, -2)
    return y.reshape(*y.shape[:-2], -1)


class TiledDense(nn.Module):
    """In-graph tiled linear — ZeRO-3 gather granularity.

    Drop-in for ``nn.Dense`` where the kernel would dominate the
    per-layer allgather: the kernel is created ``[tiles, In, Out/tiles]``
    (the tile axis an independently shardable leading dim) and applied
    with :func:`tiled_dense`. ``features`` must divide evenly by
    ``tiles``.
    """

    features: int
    tiles: int
    use_bias: bool = True
    dtype: object = None
    kernel_init: object = None

    @nn.compact
    def __call__(self, x):
        if self.features % self.tiles != 0:
            raise ValueError(f"features={self.features} not divisible "
                             f"by tiles={self.tiles}")
        ot = self.features // self.tiles
        k_init = self.kernel_init or nn.initializers.lecun_normal()
        kernel = self.param(
            "kernel",
            # init as one [In, Out] draw then tile-split, so the
            # distribution matches the untiled layer exactly
            lambda rng, shape: k_init(rng, (shape[1], self.features)
                                      ).reshape(shape[1], self.tiles, ot
                                                ).transpose(1, 0, 2),
            (self.tiles, x.shape[-1], ot))
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.tiles, ot))
                if self.use_bias else None)
        return tiled_dense(
            x.astype(self.dtype) if self.dtype else x, kernel, bias)
