"""ZeRO-Offload optimizer tier.

Capability parity with the reference's CPU/NVMe optimizer offload
(``runtime/zero/stage_1_and_2.py:1074-1223`` cpu-offload path and
``runtime/swap_tensor/partitioned_optimizer_swapper.py:27``): fp32 master
weights and Adam moments live off-chip; each accumulation boundary streams
grads device→host, runs the native C++ ``cpu_adam`` kernel
(``csrc/adam/cpu_adam.cpp``), and streams updated params host→device. With
``device="nvme"`` the moment buffers are ``np.memmap``-backed files under
``nvme_path`` so the OS pages optimizer state to disk on demand — the
swap-tensor capability without a bespoke pager (the aio op remains available
for explicit block swaps).
"""

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.pytree import flatten_with_path_strings


class HostOffloadOptimizer:
    def __init__(self, lr: float, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 gradient_clipping: float = 0.0,
                 device: str = "cpu", nvme_path: Optional[str] = None):
        from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam

        self.opt = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps,
                                    weight_decay=weight_decay,
                                    adamw_mode=adamw_mode)
        self.clip = float(gradient_clipping or 0.0)
        self.device = device
        self.nvme_path = nvme_path
        self._treedef = None
        self._shapes: Dict[str, Tuple[int, ...]] = {}
        if device == "nvme" and not nvme_path:
            raise ValueError("offload_optimizer.device=nvme requires nvme_path")

    # ------------------------------------------------------------------
    def init_from_params(self, params_tree: Any):
        """Adopt the initial device params as fp32 host masters."""
        import jax

        host = jax.device_get(params_tree)
        flat, self._treedef = flatten_with_path_strings(host)
        self._paths = [p for p, _ in flat]
        for path, leaf in flat:
            arr = np.asarray(leaf, np.float32)
            self._shapes[path] = arr.shape
            self.opt.register_param(path, arr)
            if self.device == "nvme":
                self._moments_to_memmap(path)
        n = sum(int(np.prod(s)) for s in self._shapes.values())
        log_dist(f"[offload] host optimizer holds {n/1e6:.1f}M fp32 masters "
                 f"on {self.device}", ranks=[0])

    def _moments_to_memmap(self, path: str):
        st = self.opt._state[path]
        os.makedirs(self.nvme_path, exist_ok=True)
        for key in ("exp_avg", "exp_avg_sq"):
            fname = os.path.join(
                self.nvme_path, f"{path.replace('/', '_')}.{key}.mm")
            mm = np.memmap(fname, dtype=np.float32, mode="w+",
                           shape=st[key].shape)
            mm[:] = st[key]
            st[key] = mm

    # ------------------------------------------------------------------
    def apply(self, grads_tree: Any, lr: float, loss_scale: float = 1.0,
              check_overflow: bool = False):
        """One optimizer step on host.

        Returns ``(new_params_flat, overflow, grad_norm)`` where
        ``new_params_flat`` is ``{path: fp32 ndarray}`` (None on overflow).
        Mirrors the compiled ``apply_step`` semantics: unscale → overflow
        check → global-norm clip → adam → masters back.
        """
        import jax

        host_grads = jax.device_get(grads_tree)
        flat, _ = flatten_with_path_strings(host_grads)
        inv = 1.0 / float(loss_scale)
        grads: Dict[str, np.ndarray] = {}
        sq_sum = 0.0
        overflow = False
        for path, leaf in flat:
            g = np.asarray(leaf, np.float32) * inv
            if check_overflow and not np.isfinite(g).all():
                overflow = True
            grads[path] = g
            sq_sum += float(np.sum(np.square(g, dtype=np.float64)))
        grad_norm = float(np.sqrt(sq_sum))
        if overflow:
            return None, True, grad_norm
        if self.clip > 0 and grad_norm > self.clip:
            coef = self.clip / (grad_norm + 1e-6)
            for g in grads.values():
                g *= coef
        self.opt.step(grads, lr=lr)
        new_params = {p: self.opt.get_param(p).reshape(self._shapes[p])
                      for p in grads}
        return new_params, False, grad_norm

    def params_tree(self):
        """Current masters as the original pytree structure."""
        import jax

        leaves = [self.opt.get_param(p).reshape(self._shapes[p])
                  for p in self._paths]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # checkpoint surface
    def state_dict(self):
        return self.opt.state_dict()

    def load_state_dict(self, sd):
        self.opt.load_state_dict(sd)

    def load_flat_state(self, flat: Dict[str, Any]):
        """Restore from checkpoint-flattened keys
        (``state/<param/path>/exp_avg`` …); param paths themselves contain
        ``/`` so reconstruction walks the registered paths explicitly."""
        state = {}
        for path in self._paths:
            entry = {}
            for key in ("param", "exp_avg", "exp_avg_sq"):
                entry[key] = np.ascontiguousarray(
                    np.asarray(flat[f"state/{path}/{key}"], np.float32))
            state[path] = entry
        self.opt.load_state_dict({"step": int(flat["step"]),
                                  "lr": float(flat["lr"]),
                                  "state": state})
        if self.device == "nvme":
            # keep moments file-backed after restore (loading must not
            # silently upgrade them to RAM-resident arrays)
            for path in self._paths:
                self._moments_to_memmap(path)
