"""Vocab-tiled embedding/LM-head for layers that exceed the device budget.

Capability parity with the reference ``TiledLinear``
(``runtime/zero/tiling.py:27``): a single linear too large for device
memory is computed in slices, trading one resident ``[V, C]`` weight for
``[Vt, C]`` tiles. The TPU-native shape of the problem is the tied
embedding/LM-head of huge-vocab models (the 176B-class configs in
BASELINE.json): here

- the fp32 table stays HOST-resident (the Infinity tier's master copy);
- embedding forward is a host gather (``wte[ids]``) shipping ``[B, T, C]``
  to the chip — never the table;
- the LM-head cross-entropy streams ``[Vt, C]`` weight tiles through a
  jitted per-tile kernel with an online (running max / sum-exp)
  softmax — the flash-attention trick applied to the vocab axis — and a
  second streamed pass for the backward, so peak device memory is
  ``O(B*T*C + Vt*C)`` regardless of V;
- weight gradients land tile-by-tile in a host accumulator; the
  embedding backward scatter-adds into the same accumulator (tied head).

Used by ``ZeroInfinityEngine`` when the table exceeds
``offload_param.buffer_size`` (the reference knob bounding device staging
buffers).
"""

import jax
import jax.numpy as jnp
import numpy as np


class TiledEmbedHead:
    """Host-resident tied embedding/head streamed in vocab tiles."""

    def __init__(self, vocab_size: int, n_embd: int, vocab_tile: int,
                 dtype=jnp.float32):
        self.V = int(vocab_size)
        self.C = int(n_embd)
        self.Vt = max(128, min(int(vocab_tile), self.V))
        # wire dtype for H2D traffic: tiles/embeddings cross PCIe at the
        # model's compute precision (the kernels cast to h.dtype anyway,
        # so shipping fp32 for a bf16 model would double transfer bytes)
        self.dtype = np.dtype(dtype) if dtype != jnp.bfloat16 else \
            jnp.bfloat16.dtype
        self.n_tiles = -(-self.V // self.Vt)
        self._jit_pass1 = jax.jit(self._pass1)
        self._jit_pass2 = jax.jit(self._pass2)
        self._jit_finish = jax.jit(self._finish)

    # -- embedding ------------------------------------------------------
    def embed_gather(self, wte_host: np.ndarray, ids: np.ndarray):
        """Host gather; only [B, T, C] crosses PCIe, never [V, C]."""
        return np.asarray(wte_host)[np.asarray(ids)].astype(self.dtype)

    def embed_scatter_grad(self, gwte_host: np.ndarray, ids: np.ndarray,
                           demb: np.ndarray) -> None:
        """Embedding backward: scatter-add rows into the host accumulator."""
        flat_ids = np.asarray(ids).reshape(-1)
        flat_g = np.asarray(demb, np.float32).reshape(-1, self.C)
        np.add.at(gwte_host, flat_ids, flat_g)

    # -- per-tile kernels (compiled once; tile shape static) ------------
    @staticmethod
    def _pass1(h, w, start, labels, m, s, gold):
        """Online logsumexp + gold-logit accumulation for one tile."""
        l = jnp.einsum("btc,vc->btv", h, w.astype(h.dtype),
                       preferred_element_type=jnp.float32)
        m_j = jnp.max(l, axis=-1)
        m_new = jnp.maximum(m, m_j)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(l - m_new[..., None]), axis=-1)
        vt = w.shape[0]
        in_tile = (labels >= start) & (labels < start + vt)
        idx = jnp.clip(labels - start, 0, vt - 1)
        gold = gold + jnp.where(
            in_tile, jnp.take_along_axis(l, idx[..., None], axis=-1)[..., 0],
            0.0)
        return m_new, s, gold

    @staticmethod
    def _finish(m, s, gold, labels, ignore_index=-100):
        valid = labels != ignore_index
        logz = m + jnp.log(s)
        nll = (logz - gold) * valid
        n = jnp.maximum(valid.sum(), 1)
        # coef: d(mean nll)/d(per-token nll), zero on ignored tokens
        coef = valid.astype(jnp.float32) / n.astype(jnp.float32)
        return nll.sum() / n, logz, coef

    @staticmethod
    def _pass2(h, w, start, labels, logz, coef):
        """Backward for one tile: recompute logits (remat), softmax minus
        one-hot, emit dh-partial (device) and dw (→ host)."""
        l = jnp.einsum("btc,vc->btv", h, w.astype(h.dtype),
                       preferred_element_type=jnp.float32)
        p = jnp.exp(l - logz[..., None])
        vt = w.shape[0]
        in_tile = (labels >= start) & (labels < start + vt)
        idx = jnp.clip(labels - start, 0, vt - 1)
        onehot = (jnp.arange(vt)[None, None, :] == idx[..., None]) \
            & in_tile[..., None]
        dl = coef[..., None] * (p - onehot.astype(jnp.float32))
        dh = jnp.einsum("btv,vc->btc", dl, w.astype(jnp.float32))
        dw = jnp.einsum("btv,btc->vc", dl, h.astype(jnp.float32))
        return dh, dw  # both fp32; caller accumulates in fp32

    def _stream_tiles(self, wte_host: np.ndarray, device):
        """Double-buffered tile stream: tile j+1 transfers while the
        caller's kernel runs on tile j. Shared by both passes and eval."""
        def put(j):
            lo = j * self.Vt
            hi = min(lo + self.Vt, self.V)
            # the remainder tile keeps its true size — jit compiles one
            # extra kernel variant instead of padding the partition
            # function with fake rows
            return lo, jax.device_put(
                np.asarray(wte_host[lo:hi]).astype(self.dtype), device)

        nxt = put(0)
        for j in range(self.n_tiles):
            cur, nxt = nxt, (put(j + 1) if j + 1 < self.n_tiles else None)
            yield cur

    def _pass1_all(self, h, wte_host, labels_d, device):
        B, T = labels_d.shape
        m = jnp.full((B, T), -jnp.inf, jnp.float32)
        s = jnp.zeros((B, T), jnp.float32)
        gold = jnp.zeros((B, T), jnp.float32)
        for lo, w_dev in self._stream_tiles(wte_host, device):
            m, s, gold = self._jit_pass1(h, w_dev, jnp.int32(lo),
                                         labels_d, m, s, gold)
        return m, s, gold

    # -- streamed loss (forward only: eval path) -----------------------
    def loss_only(self, h, wte_host: np.ndarray, labels, device=None):
        device = device or jax.devices()[0]
        labels_d = jax.device_put(jnp.asarray(labels), device)
        m, s, gold = self._pass1_all(h, wte_host, labels_d, device)
        loss, _, _ = self._jit_finish(m, s, gold, labels_d)
        return loss

    # -- streamed loss fwd+bwd -----------------------------------------
    def loss_and_grads(self, h, wte_host: np.ndarray, labels,
                       gwte_host: np.ndarray, device=None):
        """Streaming cross-entropy over the host table.

        ``h``: device ``[B, T, C]`` (post final-LN); ``labels``: shifted
        target ids (``-100`` ignored). Returns ``(loss, dh)`` on device;
        tile weight grads accumulate into ``gwte_host`` in place.
        """
        device = device or jax.devices()[0]
        labels_d = jax.device_put(jnp.asarray(labels), device)
        # pass 1 (double-buffered stream; peak = 2 tiles)
        m, s, gold = self._pass1_all(h, wte_host, labels_d, device)
        loss, logz, coef = self._jit_finish(m, s, gold, labels_d)
        # pass 2: stream again (remat of the logits), grads to host.
        # dh accumulates in fp32 - a bf16 running sum over n_tiles would
        # feed ~n_tiles * 2^-9 relative rounding into the whole backward
        dh = jnp.zeros(h.shape, jnp.float32)
        for lo, w_dev in self._stream_tiles(wte_host, device):
            dh_j, dw = self._jit_pass2(h, w_dev, jnp.int32(lo), labels_d,
                                       logz, coef)
            dh = dh + dh_j
            gwte_host[lo:lo + dw.shape[0]] += np.asarray(
                jax.device_get(dw), np.float32)
        return loss, dh.astype(h.dtype)
