"""Bucketed, wire-compressed gradient reduction.

The reference reduces gradients through the IPG ("independent parallel
gradient") machinery: grads are copied into fixed-byte buckets as backward
hooks fire and each bucket's allreduce launches while the rest of backward
still runs (``runtime/zero/stage_1_and_2.py:836-942``,
``reduce_bucket_size``). Under XLA there are no hooks — but the same
overlap falls out of dataflow: emit one *independent* collective per
bucket and the latency-hiding scheduler starts bucket k's collective as
soon as its last gradient is produced, while later buckets' backward
segments are still computing (T3, arxiv 2401.16677, shows this
backward/collective overlap is the second half of the compressed-wire
win). One tail-barrier psum of the whole gradient pytree — what a naive
``psum(grads)`` compiles to — cannot overlap anything.

Bucketing walks the gradient leaves in *reverse* flatten order: autodiff
produces the last layers' gradients first, so the reverse walk approximates
completion order and the first buckets' collectives can issue while the
early layers' backward is still in flight. Leaves are flattened and
concatenated per bucket so each collective carries one contiguous operand
(the reference's flat IPG buffer).

Wire tiers (``comm_quantization.dtype``): ``"none"`` full-width psum,
``"int8"`` the EQuARX-style two-leg quantized allreduce
(``runtime/comm/quantized.py``). The 1-bit tier needs error-feedback state
and therefore lives in the 1-bit optimizer family
(``runtime/fp16/onebit/``), not in this stateless path.

This is also the ZeRO reduce path: at stages >= 2 the engine constrains the
returned (replicated) gradients to their scattered shardings immediately
outside the ``shard_map``, which lowers to a local slice — the cross-wire
part of the reduction happens entirely here, on the compressed carrier.
"""

from typing import List, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.comm.quantized import (
    COMM_DTYPES,
    dense_allreduce,
    int8_allreduce,
)

DEFAULT_BUCKET_BYTES = 16 * 1024 * 1024


def bucket_by_bytes(leaves: Sequence, bucket_bytes: int) -> List[List[int]]:
    """Partition leaf indices into buckets of at most ``bucket_bytes``
    (f32 wire bytes), walking leaves in reverse order (module docstring).
    A leaf larger than the budget gets a bucket of its own."""
    budget = max(1, int(bucket_bytes))
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaves))):
        nbytes = int(leaves[i].size) * 4
        if cur and cur_bytes + nbytes > budget:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def reduce_gradients(grads, axis_name, axis_size: int,
                     comm_dtype: str = "none",
                     group_size: int = 1024,
                     bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                     mean: bool = True):
    """Mean-reduce a gradient pytree over ``axis_name`` in byte-budget
    buckets, one independent collective per bucket (must run inside
    ``shard_map`` with ``axis_name`` bound). Returns f32 leaves in the
    input structure."""
    if comm_dtype not in COMM_DTYPES or comm_dtype == "1bit":
        raise ValueError(
            f"comm_dtype must be 'none' or 'int8' here (got {comm_dtype!r}); "
            "the 1-bit tier carries error feedback in optimizer state — use "
            "the 1-bit optimizer family")
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    out = [None] * len(leaves)
    for bucket in bucket_by_bytes(leaves, bucket_bytes):
        vec = jnp.concatenate(
            [leaves[i].reshape(-1).astype(jnp.float32) for i in bucket]) \
            if len(bucket) > 1 else \
            leaves[bucket[0]].reshape(-1).astype(jnp.float32)
        if comm_dtype == "int8":
            red = int8_allreduce(vec, axis_name, axis_size,
                                 group_size=group_size, mean=mean)
        else:
            red = dense_allreduce(vec, axis_name, axis_size, mean=mean)
        offset = 0
        for i in bucket:
            n = int(leaves[i].size)
            out[i] = jax.lax.dynamic_slice_in_dim(red, offset, n).reshape(
                leaves[i].shape)
            offset += n
    return jax.tree_util.tree_unflatten(treedef, out)
