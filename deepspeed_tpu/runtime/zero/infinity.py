"""ZeRO-Infinity parameter-offload tier: layer-streamed training with
host-resident parameters.

Capability parity with the reference's ZeRO-3 parameter offload
(``runtime/zero/partition_parameters.py`` + the swap-tensor engines
``runtime/swap_tensor/partitioned_param_swapper.py:35`` and
``pipelined_optimizer_swapper.py:55``): models whose parameters exceed
device HBM train by keeping fp32 masters (and Adam moments) in host RAM —
or NVMe stride files behind the aio-backed pipelined swapper
(``runtime/zero/swapper.py``) — and streaming ONE layer's weights to the
chip at a time.

TPU-native form: where the reference hooks ``nn.Module`` forwards to
allgather/release parameter shards, here the transformer stack's scanned
parameter layout (leading layer axis) IS the streaming schedule:

- forward: layer ``l+1``'s weights are ``jax.device_put`` (async H2D)
  while the jitted block program runs layer ``l`` — a double-buffered
  prefetch, the analog of the reference's ``AsyncPartitionedParameterSwapper``
  prefetch pipeline; per-layer activations stay on device.
- backward: the same stream in reverse; each layer's VJP recomputes the
  block forward (per-layer activation checkpointing) and its parameter
  gradients copy device→host asynchronously while the next layer's VJP
  runs.
- update: the native C++ ``cpu_adam`` kernel (csrc/adam/cpu_adam.cpp)
  updates masters in place; with ``offload_param.device = "nvme"`` the
  block masters and Adam moments live in per-kind NVMe stride files and
  the update sweep is layer-pipelined through the C++ aio op — read
  ``l+1`` / update ``l`` / write ``l-1`` concurrently, host RAM bounded
  by the staging pool (reference ``pipelined_optimizer_swapper.py:55``).

The device footprint is: embeddings + head + TWO layer-weight buffers +
activations — independent of depth. Engine surface matches
``DeepSpeedEngine`` (``forward``/``backward``/``step``/checkpointing), so
``initialize()`` returns it transparently when the config asks for
``zero_optimization.offload_param``.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.utils.logging import log_dist, logger

_BLOCK_PATH = ("transformer", "h", "block")


def wants_param_offload(config) -> bool:
    """Light peek at a raw config (dict or json path) for
    ``zero_optimization.offload_param.device`` in {cpu, nvme} — decides the
    engine class before full parsing (reference ``initialize`` selects the
    ZeRO-3 offload machinery the same way)."""
    if isinstance(config, (str, os.PathLike)):
        import json

        try:
            with open(config) as f:
                config = json.load(f)
        except (OSError, ValueError):
            return False
    if not isinstance(config, dict):
        return False
    zero = config.get("zero_optimization") or {}
    if zero.get("cpu_offload_param") is True:
        # legacy flag the config parser migrates to offload_param.device=cpu
        return True
    off = zero.get("offload_param")
    if isinstance(off, dict):
        # mirror DeepSpeedZeroOffloadParamConfig: device defaults to "none"
        # (a section with only pin_memory etc. does NOT enable offload)
        return str(off.get("device", "none")) in ("cpu", "nvme")
    return False


class ZeroInfinityEngine:
    """Layer-streamed training engine (see module docstring).

    Scope (documented constraints, mirroring the reference's offload
    restrictions): canonical scanned decoder models (``GPT2ForTraining``),
    Adam-family optimizer (the native cpu kernel), deterministic forward
    (dropout 0, no PLD), bf16/fp32 precision, single-device compute (the
    tier exists for the few-chips/huge-model regime — the reference's
    ZeRO-Inference/Infinity single-GPU rows in BASELINE.md)."""

    def __init__(self, args=None, model=None, optimizer=None,
                 model_parameters=None, training_data=None,
                 lr_scheduler=None, mesh=None, dist_init_required=None,
                 collate_fn=None, config=None):
        del args, dist_init_required
        self._config = config if isinstance(config, DeepSpeedConfig) \
            else DeepSpeedConfig(config, world_size=1)
        from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel

        cfgm = getattr(model, "config", None)
        inner = getattr(model, "model", None)
        if cfgm is None or not isinstance(inner, GPT2LMHeadModel) \
                or not getattr(cfgm, "scan_layers", False):
            raise DeepSpeedConfigError(
                "zero_optimization.offload_param selects the layer-streamed "
                "ZeRO-Infinity tier, which supports the scanned canonical "
                "decoder family (GPT2ForTraining — serves GPT-2/OPT/BLOOM/"
                "GPT-J/NeoX weights — with scan_layers=True). Remove "
                "offload_param to train this model with the device engine, "
                "or use offload_optimizer alone for the host-optimizer tier")
        if getattr(cfgm, "dropout", 0.0) or getattr(cfgm, "pld", False):
            raise DeepSpeedConfigError(
                "offload_param streams a deterministic forward: set "
                "dropout=0 and pld=False")
        if self._config.fp16.enabled:
            raise DeepSpeedConfigError(
                "offload_param supports bf16/fp32 (fp16 loss scaling is a "
                "device-resident-state feature); use bf16 like the rest of "
                "the TPU stack")
        if mesh is not None and getattr(mesh, "world_size", 1) > 1:
            raise DeepSpeedConfigError(
                "offload_param is the single-device huge-model tier; "
                "multi-chip training uses ZeRO-3 sharding instead")
        if optimizer is not None:
            logger.warning("offload_param ignores the client optimizer; the "
                           "native cpu_adam kernel performs the update")
        self.module = model
        self.model_cfg = cfgm
        self._inner = inner
        self._device = jax.devices()[0]

        off = self._config.zero_config.offload_param
        self._nvme = off is not None and str(off.device) == "nvme"
        nvme_path = off.nvme_path if off is not None else None
        if self._nvme and not nvme_path:
            raise DeepSpeedConfigError(
                "offload_param.device=nvme requires nvme_path")

        # --- host masters (full tree) + cpu_adam moments ---
        from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer

        opt_name = (self._config.optimizer_name or "adamw").lower()
        if opt_name not in ("adam", "adamw"):
            raise DeepSpeedConfigError(
                f"offload_param requires an Adam-family optimizer; got "
                f"{opt_name!r}")
        p = self._config.optimizer_params or {}
        ooff = self._config.zero_config.offload_optimizer
        self._host_opt = HostOffloadOptimizer(
            lr=p.get("lr", 1e-3), betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8), weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=opt_name == "adamw",
            gradient_clipping=self._config.gradient_clipping,
            device="nvme" if (self._nvme or (
                ooff is not None and str(ooff.device) == "nvme")) else "cpu",
            nvme_path=nvme_path or (ooff.nvme_path if ooff else None))

        host_params = self._initial_params(model_parameters)
        self._swap = None
        if self._nvme:
            # aio-backed pipelined swapper: block masters + moments live in
            # per-kind stride files; host RAM holds only a bounded staging
            # pool (reference pipelined_optimizer_swapper.py:55). The top
            # (embeddings/head/final-LN) stays with the host optimizer —
            # it is O(vocab), not O(depth).
            from deepspeed_tpu.runtime.zero.swapper import (
                PipelinedOptimizerSwapper)

            blocks_init = host_params["transformer"]["h"]["block"]
            # validate the staging budget BEFORE the swapper constructor
            # write_all()s ~3x model bytes of stride files to disk — a
            # refusal must not leave orphaned multi-GB .bin files behind
            if off is not None and "buffer_size" in off.model_fields_set:
                n_layer = int(jax.tree_util.tree_leaves(
                    blocks_init)[0].shape[0])
                row_bytes = sum(
                    leaf.size // n_layer * 4
                    for leaf in jax.tree_util.tree_leaves(blocks_init))
                if row_bytes > off.buffer_size:
                    raise DeepSpeedConfigError(
                        f"offload_param.buffer_size={off.buffer_size} is "
                        f"below one layer's weights ({row_bytes} bytes) and "
                        "tiled-MLP streaming is unavailable on the NVMe "
                        "tier; raise buffer_size to at least one layer, or "
                        "use device='cpu' for tiled streaming")
            top_init = {k: v for k, v in host_params.items()
                        if k != "transformer"}
            self._host_opt.clip = 0.0  # global clip spans top+blocks: engine-owned
            self._swap = PipelinedOptimizerSwapper(
                nvme_path, blocks_init,
                lr=p.get("lr", 1e-3), betas=tuple(p.get("betas", (0.9, 0.999))),
                eps=p.get("eps", 1e-8),
                weight_decay=p.get("weight_decay", 0.0),
                adamw_mode=opt_name == "adamw")
            self._host_opt.init_from_params(top_init)
            self._host_params = None
            self._blocks = None
            self._gblocks = jax.tree_util.tree_map(
                lambda a: np.zeros(np.asarray(a).shape, np.float32),
                blocks_init)
            self.n_layer = self._swap.n_layers
            self._top = self._host_opt.params_tree()
        else:
            self._host_opt.init_from_params(host_params)
            # live master views: cpu_adam updates these arrays in place, so
            # the tree below always reads current weights — no per-step
            # rebuild
            self._host_params = self._host_opt.params_tree()
            self._blocks = self._host_params["transformer"]["h"]["block"]
            self._top = {k: v for k, v in self._host_params.items()
                         if k != "transformer"}
            self.n_layer = int(jax.tree_util.tree_leaves(
                self._blocks)[0].shape[0])
            self._gblocks = jax.tree_util.tree_map(
                lambda a: np.zeros(a.shape, np.float32), self._blocks)

        # --- vocab-tiled embedding/head (reference TiledLinear,
        # runtime/zero/tiling.py:27): when the tied table exceeds an
        # EXPLICIT device staging budget, it stays host-resident and
        # streams. Opt-in by setting offload_param.buffer_size below the
        # table bytes — the 100MB default must not silently flip standard
        # models (GPT-2's 154MB table) onto the slower streamed path.
        self._tiled = None
        wte = self._top.get("wte")
        if (off is not None and wte is not None
                and "buffer_size" in off.model_fields_set
                and wte.size * 4 > off.buffer_size):
            if not getattr(cfgm, "tied_head", True) or getattr(
                    cfgm, "lm_head_bias", False):
                raise DeepSpeedConfigError(
                    "vocab-tiled offload supports the tied, bias-free "
                    "embedding/head; raise offload_param.buffer_size to "
                    "keep the table on device")
            from deepspeed_tpu.runtime.zero.tiled_head import TiledEmbedHead

            V, C = wte.shape
            self._tiled = TiledEmbedHead(
                V, C, vocab_tile=max(128, off.buffer_size // (C * 4)),
                dtype=cfgm.dtype)
            self._gwte = np.zeros((V, C), np.float32)
            log_dist(
                f"[infinity] vocab-tiled head: [{V}, {C}] table stays on "
                f"host; {self._tiled.n_tiles} tiles of {self._tiled.Vt} "
                "rows stream per step", ranks=[0])

        # --- tiled-MLP rows (generic TiledLinear, reference
        # runtime/zero/tiling.py:27): when ONE layer's weights exceed the
        # staging budget, whole-row staging is impossible — the two MLP
        # matrices (the bulk of a row) stay host-resident and stream
        # through out-dim weight tiles (runtime/zero/tiling.py), while the
        # attention+LN remainder of the row stages as usual. Opt-in the
        # same way as the vocab-tiled head: an explicit buffer_size below
        # the row bytes.
        self._tiled_mlp = None
        if (off is not None and "buffer_size" in off.model_fields_set
                and self._blocks is not None):
            row_bytes = sum(
                leaf.size // self.n_layer * 4
                for leaf in jax.tree_util.tree_leaves(self._blocks))
            if row_bytes > off.buffer_size:
                if getattr(cfgm, "residual", "sequential") != "sequential":
                    raise DeepSpeedConfigError(
                        "tiled-MLP offload supports the sequential-residual "
                        "decoder family; raise offload_param.buffer_size to "
                        "stage whole layers")
                from deepspeed_tpu.runtime.zero.tiling import TiledLinear

                C = cfgm.n_embd
                Hf = 4 * C
                itm = 2 if cfgm.dtype == jnp.bfloat16 else 4
                self._tiled_mlp = (
                    TiledLinear(C, Hf, out_tile=max(
                        128, off.buffer_size // (C * itm)), dtype=cfgm.dtype),
                    TiledLinear(Hf, C, out_tile=max(
                        128, off.buffer_size // (Hf * itm)), dtype=cfgm.dtype))
                # grad-accumulator view excluding the tiled matrices (their
                # grads land tile-by-tile via TiledLinear.grads)
                self._gblocks_rest = {k: v for k, v in self._gblocks.items()
                                      if k != "mlp"}
                log_dist(
                    f"[infinity] tiled-MLP rows: layer bytes {row_bytes} "
                    f"exceed budget {off.buffer_size}; c_fc streams "
                    f"{self._tiled_mlp[0].n_tiles} tiles, c_proj "
                    f"{self._tiled_mlp[1].n_tiles}", ranks=[0])

        self._top_dev = self._commit_top()
        self._gtop = None       # device-accumulated top grads
        self._compiled = {}
        self._last_loss = None
        self._last_grad_norm = None
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0

        # lr schedule (host-evaluated; same config surface as the engine)
        self._schedule_fn = None
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is None and self._config.scheduler_name:
            from deepspeed_tpu.runtime.lr_schedules import (
                LRScheduler, get_lr_schedule_fn)

            self._schedule_fn = get_lr_schedule_fn(
                self._config.scheduler_name,
                self._config.scheduler_params or {})
            self.lr_scheduler = LRScheduler(self._schedule_fn)

        self.training_dataloader = None
        if training_data is not None:
            from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

            self.training_dataloader = DeepSpeedDataLoader(
                training_data,
                batch_size=self._config.train_micro_batch_size_per_gpu,
                collate_fn=collate_fn)
        self.optimizer = self._host_opt
        n = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(self._host_params))
        log_dist(
            f"ZeroInfinityEngine: {self.n_layer} streamed layers, "
            f"{n / 1e6:.1f}M params on "
            f"{'nvme' if self._nvme else 'host'}, device keeps "
            f"embeddings/head + 2 layer buffers", ranks=[0])

    # ------------------------------------------------------------------
    def _initial_params(self, model_parameters):
        if model_parameters is not None:
            host = jax.device_get(model_parameters)
            return jax.tree_util.tree_map(
                lambda a: np.asarray(a, np.float32), host)
        # layer-streamed init (the reference's zero.Init partitions params
        # at construction the same way): the device materializes ONE
        # block's params at a time; each row lands straight in the host
        # stack, so the full tree never touches HBM. Top-level params are
        # built host-side with the model's init distributions.
        from deepspeed_tpu.models.gpt2 import Block

        cfg = self.model_cfg
        key = jax.random.PRNGKey(0)
        x = jnp.zeros((1, min(8, cfg.n_positions), cfg.n_embd), cfg.dtype)
        block = Block(cfg)
        L = cfg.n_layer

        def init_row(l):
            return jax.device_get(
                block.init(jax.random.fold_in(key, l), x))["params"]

        row0 = init_row(0)
        blocks = jax.tree_util.tree_map(
            lambda a: np.empty((L,) + a.shape, np.float32), row0)

        def fill(l, row):
            jax.tree_util.tree_map(
                lambda buf, a: buf.__setitem__(l, np.asarray(a, np.float32)),
                blocks, row)

        fill(0, row0)
        for l in range(1, L):
            fill(l, init_row(l))

        rng = np.random.default_rng(0)
        C, V = cfg.n_embd, cfg.vocab_size
        params = {
            "wte": rng.normal(0.0, 0.02, (V, C)).astype(np.float32),
            "ln_f": {"scale": np.ones(C, np.float32),
                     "bias": np.zeros(C, np.float32)},
            "transformer": {"h": {"block": blocks}},
        }
        if cfg.position_embedding == "learned":
            params["wpe"] = rng.normal(
                0.0, 0.01, (cfg.n_positions + cfg.position_offset,
                             C)).astype(np.float32)
        if cfg.embedding_layernorm:
            params["emb_ln"] = {"scale": np.ones(C, np.float32),
                                "bias": np.zeros(C, np.float32)}
        if not cfg.tied_head:
            params["lm_head"] = rng.normal(0.0, 0.02, (V, C)).astype(
                np.float32)
            if cfg.lm_head_bias:
                params["lm_head_bias"] = np.zeros(V, np.float32)
        return params

    # ------------------------------------------------------------------
    # compiled per-layer programs (one compile each; reused for all layers)
    def _fns(self, B, T):
        key = (B, T)
        if key in self._compiled:
            return self._compiled[key]
        import flax.linen as nn

        from deepspeed_tpu.models.gpt2 import (Block, _remat_block,
                                               lm_head_loss, shift_labels)

        cfg = self.model_cfg
        block = _remat_block(cfg)(cfg) if cfg.remat else Block(cfg)

        def block_fwd(bp, x):
            return block.apply({"params": bp}, x, True)

        def ln(name, params, x):
            return nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                dtype=cfg.dtype).apply(
                {"params": params[name]}, x)

        def embed(top, ids):
            x = top["wte"][ids].astype(cfg.dtype)
            if cfg.position_embedding == "learned":
                x = x + top["wpe"][None, cfg.position_offset:
                                   cfg.position_offset + T].astype(cfg.dtype)
            if cfg.embedding_layernorm:
                x = ln("emb_ln", top, x)
            return x

        def head_loss(top, hidden, labels):
            x = ln("ln_f", top, hidden)
            head_w = top["wte"] if cfg.tied_head else top["lm_head"]
            bias = top["lm_head_bias"] if cfg.lm_head_bias else None
            # shared head policy (models/gpt2.py lm_head_loss); the tight
            # 1 GB dense budget is intentional — the full [B, T, V] fp32
            # logits tensor is exactly the HBM spike this tier exists to
            # avoid, and there is no remat headroom to spend
            return lm_head_loss(x, head_w, shift_labels(labels), bias=bias,
                                dense_budget=1_000_000_000)

        def block_vjp(bp, x, dy):
            _, vjp = jax.vjp(block_fwd, bp, x)
            dbp, dx = vjp(dy)
            return dbp, dx

        def head_vjp(top, hidden, labels):
            (loss, (dtop, dx)) = jax.value_and_grad(
                head_loss, argnums=(0, 1))(top, hidden, labels)
            return loss, dtop, dx

        def embed_vjp(top, ids, dx):
            _, vjp = jax.vjp(lambda t: embed(t, ids), top)
            return vjp(dx)[0]

        def top_add(acc, new):
            return jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), acc, new)

        fns = {
            "block_fwd": jax.jit(block_fwd),
            "block_vjp": jax.jit(block_vjp),
            "embed": jax.jit(embed),
            "head_vjp": jax.jit(head_vjp),
            "embed_vjp": jax.jit(embed_vjp),
            "top_add": jax.jit(top_add, donate_argnums=(0,)),
            "head_loss": jax.jit(head_loss),
        }
        if self._tiled is not None:
            # tiled tier: the wte gather/head matmul live OUTSIDE these
            # programs (host gather + streamed tiles); the jitted pieces
            # are everything around them
            def embed_rest(top, emb):
                x = emb.astype(cfg.dtype)
                if cfg.position_embedding == "learned":
                    x = x + top["wpe"][None, cfg.position_offset:
                                       cfg.position_offset + T].astype(
                        cfg.dtype)
                if cfg.embedding_layernorm:
                    x = ln("emb_ln", top, x)
                return x

            def lnf(top, h):
                return ln("ln_f", top, h)

            fns["embed_rest"] = jax.jit(embed_rest)
            fns["embed_rest_vjp"] = jax.jit(
                lambda top, emb, g: jax.vjp(embed_rest, top, emb)[1](g))
            fns["lnf"] = jax.jit(lnf)
            fns["lnf_vjp"] = jax.jit(
                lambda top, h, g: jax.vjp(lnf, top, h)[1](g))
        if self._tiled_mlp is not None:
            # tiled-MLP row programs: the block splits at the MLP matmuls
            # (those stream host tiles outside jit); pre_mlp covers
            # ln_1 → attention → residual → ln_2, all deterministic
            from deepspeed_tpu.models.gpt2 import CausalSelfAttention

            def pre_mlp(bp_rest, x):
                h1 = ln("ln_1", bp_rest, x)
                attn_out = CausalSelfAttention(cfg).apply(
                    {"params": bp_rest["attn"]}, h1, True)
                x1 = x + attn_out
                return x1, ln("ln_2", bp_rest, x1)

            def pre_mlp_vjp(bp_rest, x, d_x1, d_h):
                _, vjp = jax.vjp(pre_mlp, bp_rest, x)
                return vjp((d_x1, d_h))

            import flax.linen as fnn

            def act_fwd(u):
                if cfg.activation == "relu":
                    return fnn.relu(u)
                return fnn.gelu(u,
                                approximate=cfg.activation != "gelu_exact")

            fns["pre_mlp"] = jax.jit(pre_mlp)
            fns["pre_mlp_vjp"] = jax.jit(pre_mlp_vjp)
            fns["act_fwd"] = jax.jit(act_fwd)
            fns["act_vjp"] = jax.jit(
                lambda u, da: jax.vjp(act_fwd, u)[1](da)[0])
            fns["resid_add"] = jax.jit(lambda x1, y2: x1 + y2)
        self._compiled[key] = fns
        return fns

    # -- tiled-MLP row streaming (generic TiledLinear path) -------------
    def _block_fwd_tiled(self, l, rest_dev, x, fns):
        tl1, tl2 = self._tiled_mlp
        fc = self._blocks["mlp"]["c_fc"]
        pj = self._blocks["mlp"]["c_proj"]
        x1, h = fns["pre_mlp"](rest_dev, x)
        u = tl1.forward(h, fc["kernel"][l], fc["bias"][l],
                        device=self._device)
        a = fns["act_fwd"](u)
        y2 = tl2.forward(a, pj["kernel"][l], pj["bias"][l],
                         device=self._device)
        return fns["resid_add"](x1, y2)

    def _block_vjp_tiled(self, l, rest_dev, x, dy, fns):
        """Backward for one tiled row: recompute x1/h/u/a from the saved
        block input (weight remat — the big matrices stream again), then
        chain the streamed VJPs. Tile weight grads land straight in the
        host accumulators; the returned dbp covers only the staged
        (attention/LN) part of the row."""
        tl1, tl2 = self._tiled_mlp
        fc = self._blocks["mlp"]["c_fc"]
        pj = self._blocks["mlp"]["c_proj"]
        gfc = self._gblocks["mlp"]["c_fc"]
        gpj = self._gblocks["mlp"]["c_proj"]
        x1, h = fns["pre_mlp"](rest_dev, x)
        u = tl1.forward(h, fc["kernel"][l], fc["bias"][l],
                        device=self._device)
        a = fns["act_fwd"](u)
        d_a = tl2.grads(a, pj["kernel"][l], dy, gpj["kernel"][l],
                        gpj["bias"][l], device=self._device)
        d_u = fns["act_vjp"](u, d_a)
        d_h = tl1.grads(h, fc["kernel"][l], d_u, gfc["kernel"][l],
                        gfc["bias"][l], device=self._device)
        dbp_rest, dx = fns["pre_mlp_vjp"](rest_dev, x, dy, d_h)
        return dbp_rest, dx

    def _commit_top(self):
        """Device copy of the top params; a tiled table stays on host."""
        top = ({k: v for k, v in self._top.items() if k != "wte"}
               if self._tiled is not None else self._top)
        return jax.device_put(top, self._device)

    def _row(self, l: int):
        """Layer ``l``'s weights as a host tree of contiguous row views —
        the unit the H2D stream moves (host-RAM tier). In tiled-MLP mode
        the staged row excludes the MLP matrices (those stream as weight
        tiles inside the block programs)."""
        blocks = (self._blocks if self._tiled_mlp is None else
                  {k: v for k, v in self._blocks.items() if k != "mlp"})
        return jax.tree_util.tree_map(lambda a: a[l], blocks)

    def _fetch_row(self, l: int, prefetch: int = -1):
        """Layer ``l``'s weights on device; NVMe tier streams through the
        aio staging pool (issue the *next* read before waiting on this
        one, so disk I/O overlaps the running block program)."""
        if self._swap is None:
            return jax.device_put(self._row(l), self._device)
        if 0 <= prefetch < self.n_layer:
            self._swap.prefetch_params(prefetch)
        views = self._swap.get_params(l)
        # copy out of the staging buffer before release: device_put may
        # read the host memory asynchronously after this returns
        row = jax.tree_util.tree_map(np.array, views)
        self._swap.release_params(l)
        return jax.device_put(row, self._device)

    # ------------------------------------------------------------------
    def forward(self, batch):
        if isinstance(batch, dict):
            ids = np.asarray(batch["input_ids"])
            labels = np.asarray(batch.get("labels", batch["input_ids"]))
        elif isinstance(batch, (tuple, list)):
            ids, labels = np.asarray(batch[0]), np.asarray(batch[1])
        else:
            ids = labels = np.asarray(batch)
        B, T = ids.shape
        fns = self._fns(B, T)
        dev = self._device
        L = self.n_layer

        # ---- forward stream: prefetch l+1 while l computes ----
        if self._tiled is not None:
            # host gather: [B, T, C] crosses to the chip, never the table
            emb_dev = jax.device_put(
                self._tiled.embed_gather(self._top["wte"], ids), dev)
            x = fns["embed_rest"](self._top_dev, emb_dev)
        else:
            x = fns["embed"](self._top_dev, jax.device_put(ids, dev))
        acts = [x]
        if self._swap is not None:
            self._swap.prefetch_params(0)
        nxt = self._fetch_row(0, prefetch=1)
        for l in range(L):
            cur, nxt = nxt, (self._fetch_row(l + 1, prefetch=l + 2)
                             if l + 1 < L else None)
            x = (self._block_fwd_tiled(l, cur, x, fns)
                 if self._tiled_mlp is not None
                 else fns["block_fwd"](cur, x))
            acts.append(x)

        labels_d = jax.device_put(labels, dev)
        if self._tiled is not None:
            from deepspeed_tpu.models.gpt2 import shift_labels

            h = acts[-1]
            hln = fns["lnf"](self._top_dev, h)
            # streamed vocab tiles: online-softmax fwd + remat bwd; tile
            # weight grads accumulate straight into the host table grad
            loss, dhln = self._tiled.loss_and_grads(
                hln, self._top["wte"], shift_labels(labels_d),
                self._gwte, device=dev)
            dtop, dx = fns["lnf_vjp"](self._top_dev, h, dhln)
        else:
            loss, dtop, dx = fns["head_vjp"](self._top_dev, acts[-1],
                                             labels_d)

        # ---- backward stream: reverse prefetch; dparams D2H overlaps the
        # next layer's VJP (async host copy, consumed one step later) ----
        pending = None  # (layer, device grads) awaiting host accumulation
        if self._swap is not None:
            self._swap.prefetch_params(L - 1)
        nxt = self._fetch_row(L - 1, prefetch=L - 2)
        for l in range(L - 1, -1, -1):
            cur, nxt = nxt, (self._fetch_row(l - 1, prefetch=l - 2)
                             if l > 0 else None)
            if self._tiled_mlp is not None:
                dbp, dx = self._block_vjp_tiled(l, cur, acts[l], dx, fns)
            else:
                dbp, dx = fns["block_vjp"](cur, acts[l], dx)
            for leaf in jax.tree_util.tree_leaves(dbp):
                leaf.copy_to_host_async()
            if pending is not None:
                self._accum_block(*pending)
            pending = (l, dbp)
        if pending is not None:
            self._accum_block(*pending)
        if self._tiled is not None:
            dtop_e, demb = fns["embed_rest_vjp"](self._top_dev, emb_dev, dx)
            self._tiled.embed_scatter_grad(self._gwte, ids,
                                           jax.device_get(demb))
        else:
            dtop_e = fns["embed_vjp"](self._top_dev,
                                      jax.device_put(ids, dev), dx)
        dtop = jax.tree_util.tree_map(lambda a, b: a + b, dtop, dtop_e)
        self._gtop = dtop if self._gtop is None \
            else fns["top_add"](self._gtop, dtop)
        self._last_loss = loss
        return loss

    __call__ = forward

    def _accum_block(self, l: int, dbp):
        host = jax.device_get(dbp)
        # tiled-MLP rows produce grads only for the staged (non-mlp) part;
        # the MLP tile grads already landed via TiledLinear.grads
        target = (self._gblocks if self._tiled_mlp is None
                  else self._gblocks_rest)
        def add(acc, g):
            acc[l] += np.asarray(g, np.float32)
        jax.tree_util.tree_map(add, target, host)

    def eval_loss(self, batch):
        """Streamed forward only (no gradients) — the inference/eval path."""
        if isinstance(batch, dict):
            ids = np.asarray(batch["input_ids"])
            labels = np.asarray(batch.get("labels", batch["input_ids"]))
        elif isinstance(batch, (tuple, list)):
            ids, labels = np.asarray(batch[0]), np.asarray(batch[1])
        else:
            ids = labels = np.asarray(batch)
        B, T = ids.shape
        fns = self._fns(B, T)
        if self._tiled is not None:
            emb_dev = jax.device_put(
                self._tiled.embed_gather(self._top["wte"], ids),
                self._device)
            x = fns["embed_rest"](self._top_dev, emb_dev)
        else:
            x = fns["embed"](self._top_dev,
                             jax.device_put(ids, self._device))
        if self._swap is not None:
            self._swap.prefetch_params(0)
        nxt = self._fetch_row(0, prefetch=1)
        for l in range(self.n_layer):
            cur, nxt = nxt, (self._fetch_row(l + 1, prefetch=l + 2)
                             if l + 1 < self.n_layer else None)
            x = (self._block_fwd_tiled(l, cur, x, fns)
                 if self._tiled_mlp is not None
                 else fns["block_fwd"](cur, x))
        if self._tiled is not None:
            from deepspeed_tpu.models.gpt2 import shift_labels

            hln = fns["lnf"](self._top_dev, x)
            return self._tiled.loss_only(
                hln, self._top["wte"],
                shift_labels(jax.device_put(labels, self._device)),
                device=self._device)
        return fns["head_loss"](self._top_dev, x,
                                jax.device_put(labels, self._device))

    def backward(self, loss=None, **kw):
        """Grads were produced with the loss in ``forward`` (same contract
        as ``DeepSpeedEngine.backward``)."""
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def step(self, lr_kwargs=None):
        del lr_kwargs
        if self._gtop is None:
            raise RuntimeError("step() called before any forward()")
        if self.is_gradient_accumulation_boundary():
            gas = self.gradient_accumulation_steps()
            if self._schedule_fn is not None:
                lr = float(self._schedule_fn(self.global_steps))
            elif self.lr_scheduler is not None and hasattr(
                    self.lr_scheduler, "get_lr"):
                lr = self.lr_scheduler.get_lr()
                if isinstance(lr, (list, tuple)):  # LRScheduler returns [lr]
                    lr = lr[0]
                lr = float(lr)
            else:
                lr = float((self._config.optimizer_params or {}).get(
                    "lr", 1e-3))
            if self._swap is None:
                grads = dict(jax.device_get(self._gtop))
                if self._tiled is not None:
                    grads["wte"] = self._gwte
                grads["transformer"] = {"h": {"block": self._gblocks}}
                # mean over micro-steps: apply() already multiplies grads by
                # 1/loss_scale leaf-by-leaf — no extra full-tree scaling pass
                _, _, grad_norm = self._host_opt.apply(grads, lr=lr,
                                                       loss_scale=float(gas))
                self._last_grad_norm = grad_norm
            else:
                # NVMe tier: global clip spans top + blocks, so the norm is
                # engine-owned; the pipelined swapper then updates the
                # blocks layer-by-layer while (param, m, v) records stream
                grads_top = dict(jax.device_get(self._gtop))
                if self._tiled is not None:
                    grads_top["wte"] = self._gwte
                sq = sum(float(np.sum(np.square(
                    np.asarray(g, np.float32), dtype=np.float64)))
                    for g in jax.tree_util.tree_leaves(grads_top))
                sq += sum(float(np.sum(np.square(g, dtype=np.float64)))
                          for g in jax.tree_util.tree_leaves(self._gblocks))
                grad_norm = float(np.sqrt(sq)) / gas  # norm of the mean
                clip = float(self._config.gradient_clipping or 0.0)
                clip_coef = (min(1.0, clip / (grad_norm + 1e-6))
                             if clip > 0 else 1.0)
                # top: apply() unscales by 1/loss_scale — fold the clip in
                self._host_opt.apply(grads_top, lr=lr,
                                     loss_scale=float(gas) / clip_coef)
                self._swap.step(self._gblocks, lr=lr,
                                grad_scale=clip_coef / gas)
                self._last_grad_norm = grad_norm
            # masters updated in place; only the device-resident top copy
            # needs a commit (block weights re-stream from masters anyway;
            # a tiled table never goes to device at all)
            self._top_dev = self._commit_top()
            self._gtop = None
            if self._tiled is not None:
                self._gwte.fill(0.0)
            for leaf in jax.tree_util.tree_leaves(self._gblocks):
                leaf.fill(0.0)
            self.global_steps += 1
            self.global_samples += self.train_batch_size()
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
        self.micro_steps += 1

    # ------------------------------------------------------------------
    # engine accessor surface (subset the reference exposes)
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def get_global_grad_norm(self):
        return self._last_grad_norm

    def zero_optimization_stage(self):
        return 3

    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None):
        tag = tag or f"global_step{self.global_steps}"
        d = os.path.join(str(save_dir), tag)
        os.makedirs(d, exist_ok=True)
        flat = {"step": self._host_opt.opt.step_count,
                "lr": self._host_opt.opt.lr,
                "global_steps": self.global_steps,
                "global_samples": self.global_samples,
                "micro_steps": self.micro_steps}
        for path in self._host_opt._paths:
            st = self._host_opt.opt._state[path]
            for key in ("param", "exp_avg", "exp_avg_sq"):
                flat[f"state/{path}/{key}"] = np.asarray(st[key])
        if self._swap is not None:
            # blocks live on NVMe: export them under the same path scheme
            # the host-RAM tier uses, so checkpoints stay interchangeable
            from deepspeed_tpu.utils.pytree import flatten_with_path_strings

            for key in ("param", "exp_avg", "exp_avg_sq"):
                tree = self._swap.read_full(key)
                for path, leaf in flatten_with_path_strings(tree)[0]:
                    flat[f"state/transformer/h/block/{path}/{key}"] = leaf
        np.savez(os.path.join(d, "infinity_state.npz"), **flat)
        # crash-safe pointer (same contract as DeepSpeedEngine): a crash
        # mid-write must never leave a truncated latest
        from deepspeed_tpu.runtime.resilience.integrity import (
            atomic_write_text)

        atomic_write_text(os.path.join(str(save_dir), "latest"), tag)
        log_dist(f"saved infinity checkpoint {tag} to {d}", ranks=[0])
        return True

    def load_checkpoint(self, load_dir, tag=None, **kw):
        if tag is None:
            latest = os.path.join(str(load_dir), "latest")
            if not os.path.exists(latest):
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        fname = os.path.join(str(load_dir), tag, "infinity_state.npz")
        if not os.path.exists(fname):
            from deepspeed_tpu.runtime.resilience.integrity import (
                missing_tag_error)

            raise missing_tag_error(str(load_dir), tag,
                                    f"infinity tag {tag!r}")
        with np.load(fname) as z:
            flat = {k: z[k] for k in z.files}
        self._host_opt.load_flat_state(flat)
        if self._swap is not None:
            # rebuild the per-kind stride files from the checkpoint and
            # adopt the optimizer step for bias correction
            import jax.tree_util as jtu

            prefix = "state/transformer/h/block/"
            for key in ("param", "exp_avg", "exp_avg_sq"):
                leaves = {}
                for k, v in flat.items():
                    if k.startswith(prefix) and k.endswith("/" + key):
                        leaves[k[len(prefix):-len(key) - 1]] = v
                tree = jtu.tree_unflatten(
                    self._swap.spec.treedef,
                    [leaves[p] for p in self._swap.spec.paths])
                self._swap.write_full(key, tree)
            self._swap.step_count = int(flat["step"])
            self._top = self._host_opt.params_tree()
        else:
            self._host_params = self._host_opt.params_tree()
            self._blocks = self._host_params["transformer"]["h"]["block"]
            self._top = {k: v for k, v in self._host_params.items()
                         if k != "transformer"}
        self._top_dev = self._commit_top()
        if self._tiled is not None:
            self._gwte.fill(0.0)
        self.global_steps = int(flat["global_steps"])
        self.global_samples = int(flat["global_samples"])
        self.micro_steps = int(flat["micro_steps"])
        # drop any in-flight accumulation: grads gathered before the
        # restore must not leak into the first post-restore boundary
        self._gtop = None
        for leaf in jax.tree_util.tree_leaves(self._gblocks):
            leaf.fill(0.0)
        log_dist(f"loaded infinity checkpoint {tag} from {load_dir}",
                 ranks=[0])
        return tag, {}  # same convention as DeepSpeedEngine.load_checkpoint
