"""ZeRO stages as GSPMD sharding policies.

The reference implements ZeRO with explicit bookkeeping: flat-buffer
round-robin partitions (``runtime/zero/stage_1_and_2.py:609``), grad-hook IPG
buckets (``:836-942``), and for stage 3 per-param ``ds_tensor`` shards with
gather/release hooks (``runtime/zero/partition_parameters.py:1042``,
``partitioned_param_coordinator.py:239``). On TPU all of that collapses into
*where each array lives on the mesh*:

- **stage 1**: optimizer state (m/v) sharded over the data axes; params
  replicated. XLA's weight-update sharding: grads reduce-scatter into the
  owner shard, updated weights all-gather back — the reference's
  ``allgather_bucket`` loop (``stage_1_and_2.py:1821``) becomes an output
  sharding spec.
- **stage 2**: same program — gradients never materialize replicated because
  the only consumer (the sharded update) needs 1/N of them; XLA's scheduler
  plays the role of the IPG overlap stream.
- **stage 3**: params themselves sharded; every use triggers a (scan-scoped)
  all-gather, every grad a reduce-scatter — the fetch/release coordinator
  becomes dataflow.

Sharding rule: shard the largest dimension divisible by the axis size; params
smaller than ``param_persistence_threshold`` stay replicated (mirrors
``stage3_param_persistence_threshold``).

Since the 3-axis mesh (``data x fsdp x tp``, GSPMD arXiv:2105.04663) the
one authority over *which axis shards what* is :class:`SpecLayout`:
canonical PartitionSpecs per parameter family (embeddings, attention
QKV/proj, MLP in/out, norms) on the ``tp`` axis, ZeRO layering over
``data x fsdp x expert``, and batch arrays over ``data x expert`` ONLY —
``fsdp``/``tp`` never shard the batch dimension. Training shardings,
the topology manifest, the AOT fingerprint and the serving engines all
consume the same layout, so the partitioning of a tensor family cannot
diverge between training and inference.
"""

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (AXIS_DATA, AXIS_EXPERT,
                                             AXIS_FSDP, AXIS_SEQ, AXIS_TP)

# ZeRO partitions optimizer state / ZeRO-3 params over these axes (the
# flattened product is the reference's "partition count"); the batch only
# ever shards over BATCH_AXES — fsdp buys param/opt-state memory headroom
# without forcing more data parallelism, tp never touches the batch.
ZERO_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)
BATCH_AXES = (AXIS_DATA, AXIS_EXPERT)


def hierarchical_param_axes(zero_axes: Sequence[str] = ZERO_AXES
                            ) -> Tuple[str, ...]:
    """The ZeRO axes a *hierarchical* (ZeRO++ hpZ, arXiv:2306.10209) param
    shard spans: everything but ``data`` — i.e. the shard lives inside one
    data replica, so the per-use all-gather crosses only the fsdp/expert
    wire instead of the full data x fsdp group. Optimizer and gradient
    state keep the full ``zero_axes`` partition (the once-per-step update
    path), only the per-layer-per-tick param fetch shrinks."""
    return tuple(a for a in zero_axes if a != AXIS_DATA)


def _shardable_dim(shape: Tuple[int, ...], axis_size: int,
                   taken: Sequence[Optional[str]]) -> Optional[int]:
    """Largest dim divisible by axis_size and not already sharded."""
    best, best_size = None, 0
    for i, d in enumerate(shape):
        if taken[i] is None and d % axis_size == 0 and d >= axis_size and d > best_size:
            best, best_size = i, d
    return best


def zero_partition_spec(shape: Tuple[int, ...],
                        mesh: Mesh,
                        data_axes: Optional[Sequence[str]] = None,
                        base_spec: Optional[P] = None,
                        persistence_threshold: int = 0) -> P:
    """PartitionSpec sharding ``shape`` over the (flattened) data axes,
    layered on top of ``base_spec`` (TP/expert specs from the model).

    Returns ``base_spec`` unchanged if the array is too small (persistence
    threshold) or no dim divides evenly.
    """
    if data_axes is None:
        data_axes = ZERO_AXES
    entries = list(base_spec) if base_spec is not None else []
    entries += [None] * (len(shape) - len(entries))
    used = {a for e in entries for a in (e if isinstance(e, tuple) else (e,)) if a}
    # a mesh axis may appear at most once in a spec: e.g. expert params carry
    # "expert" in their base spec, so ZeRO shards them over "data" only
    data_axes = [a for a in data_axes if mesh.shape.get(a, 1) > 1 and a not in used]
    if not data_axes:
        return base_spec if base_spec is not None else P()
    axis_size = int(np.prod([mesh.shape[a] for a in data_axes]))
    if int(np.prod(shape)) < max(persistence_threshold, axis_size):
        return P(*entries) if base_spec is not None else P()
    dim = _shardable_dim(shape, axis_size, entries)
    if dim is None:
        return P(*entries) if base_spec is not None else P()
    group = tuple(data_axes) if len(data_axes) > 1 else data_axes[0]
    entries[dim] = group
    return P(*entries)


def build_zero_shardings(params_shapes,
                         mesh: Mesh,
                         stage: int,
                         param_specs=None,
                         persistence_threshold: int = 0,
                         hierarchical: bool = False):
    """Shardings for (params, optimizer state) given a ZeRO stage.

    ``params_shapes``: pytree of ``jax.ShapeDtypeStruct`` (or arrays).
    ``param_specs``: optional pytree of base PartitionSpecs (TP rules).
    ``hierarchical``: hpZ — stage-3 *params* shard over
    :func:`hierarchical_param_axes` only (inside a data replica);
    optimizer state keeps the full :data:`ZERO_AXES` partition.
    Returns ``(param_shardings, opt_shardings)`` pytrees of NamedSharding.
    """

    def base_spec_of(leaf_spec):
        return leaf_spec if leaf_spec is not None else None

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(lambda _: None, params_shapes)

    param_axes = hierarchical_param_axes() if hierarchical else ZERO_AXES

    def param_sharding(leaf, spec):
        base = base_spec_of(spec)
        if stage >= 3:
            s = zero_partition_spec(leaf.shape, mesh,
                                    data_axes=param_axes,
                                    base_spec=base,
                                    persistence_threshold=persistence_threshold)
        else:
            s = base if base is not None else P()
        return NamedSharding(mesh, s)

    def opt_sharding(leaf, spec):
        base = base_spec_of(spec)
        if stage >= 1:
            s = zero_partition_spec(leaf.shape, mesh, base_spec=base)
        else:
            s = base if base is not None else P()
        return NamedSharding(mesh, s)

    param_shardings = jax.tree_util.tree_map(
        param_sharding, params_shapes, param_specs,
        is_leaf=lambda x: hasattr(x, "shape"))
    opt_shardings = jax.tree_util.tree_map(
        opt_sharding, params_shapes, param_specs,
        is_leaf=lambda x: hasattr(x, "shape"))
    return param_shardings, opt_shardings


def build_opt_state_shardings(opt_abstract, params_abstract, mesh: Mesh,
                              stage: int, param_specs=None):
    """Shardings for an arbitrary optimizer-state pytree.

    Optimizer states are built of (a) subtrees that mirror the params tree
    (Adam m/v, momentum buffers) — those get the per-param ZeRO⊕TP spec —
    and (b) scalars/None — replicated. Subtree matching is structural, so any
    optimizer whose state contains params-shaped pytrees works.
    """
    params_leaves, params_def = jax.tree_util.tree_flatten(params_abstract)
    _, mirrored = build_zero_shardings(params_abstract, mesh, stage=stage,
                                       param_specs=param_specs)
    rep = replicated(mesh)

    def _mirrors_params(sub) -> bool:
        if sub is None:
            return False
        try:
            leaves, treedef = jax.tree_util.tree_flatten(sub)
        except Exception:
            return False
        return (treedef == params_def
                and all(tuple(l.shape) == tuple(p.shape)
                        for l, p in zip(leaves, params_leaves)))

    def handle(sub):
        if _mirrors_params(sub):
            return mirrored
        # lone leaf without a params mirror: shard by its own shape
        if stage >= 1 and getattr(sub, "ndim", 0) > 0:
            return NamedSharding(mesh, zero_partition_spec(tuple(sub.shape), mesh))
        return rep

    # tree_map recursion handles any registered pytree node (FrozenDict,
    # struct dataclasses, ...); is_leaf stops at params-mirroring subtrees
    return jax.tree_util.tree_map(handle, opt_abstract, is_leaf=_mirrors_params)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ----------------------------------------------------------------------
# SpecLayout: the one authority over the data x fsdp x tp mesh layout
class SpecLayout:
    """Canonical named-axis partition layout (GSPMD, arXiv:2105.04663).

    ONE object answers every "which axis shards this tensor?" question
    for a mesh, consumed identically by training and inference:

    - **parameter families** (embeddings, attention QKV, attention
      output proj, MLP in, MLP out, norms) get tp-axis base
      PartitionSpecs from a ``module_inject`` policy;
    - **ZeRO** (stages 1-3) layers ``data x fsdp x expert`` sharding on
      the dims TP left alone (:func:`zero_partition_spec`);
    - **batch arrays** shard over ``batch_axes`` ONLY — by contract
      ``fsdp`` and ``tp`` never appear in a batch spec (they shard
      weights/heads, so putting them on the batch would silently change
      the global batch size).

    ``policy`` may be a TPPolicy, a policy name, or None (name "auto").
    """

    def __init__(self, mesh: Mesh, policy="auto",
                 tp_axis: str = AXIS_TP,
                 zero_axes: Sequence[str] = ZERO_AXES,
                 batch_axes: Sequence[str] = BATCH_AXES,
                 persistence_threshold: int = 0,
                 hierarchical_gather: bool = False):
        forbidden = {tp_axis, AXIS_FSDP} & set(batch_axes)
        if forbidden:
            raise ValueError(
                f"batch_axes {tuple(batch_axes)} must not contain the "
                f"tp/fsdp axes {sorted(forbidden)}: they shard weights, "
                "never the batch dimension")
        from deepspeed_tpu.parallel.topology import resolve_axis_name

        self.mesh = mesh
        # a user-built mesh may still carry the legacy "model" axis name
        # — specs must name the axis the mesh actually has, or TP would
        # silently replicate
        self.tp_axis = resolve_axis_name(mesh, tp_axis)
        self.zero_axes = tuple(zero_axes)
        self.batch_axes = tuple(batch_axes)
        self.persistence_threshold = int(persistence_threshold)
        self.hierarchical_gather = bool(hierarchical_gather)
        self._policy = policy

    @property
    def hierarchical_active(self) -> bool:
        """hpZ in effect: requested AND the mesh has a secondary (non-data)
        ZeRO axis of size > 1 to hold the replica-local shard. On a flat
        data-only mesh the flag is a no-op — the caller (engine) warns."""
        if not self.hierarchical_gather:
            return False
        return any(self.mesh.shape.get(a, 1) > 1
                   for a in hierarchical_param_axes(self.zero_axes))

    # -- policy / families ------------------------------------------------
    @property
    def policy(self):
        from deepspeed_tpu.module_inject.policies import get_tp_policy

        if isinstance(self._policy, str) or self._policy is None:
            self._policy = get_tp_policy(self._policy or "auto")
        return self._policy

    @property
    def tp_size(self) -> int:
        return int(self.mesh.shape.get(self.tp_axis, 1))

    def family_of(self, path: str, shape: Tuple[int, ...] = ()) -> str:
        """Parameter family of one param path (module docstring list)."""
        from deepspeed_tpu.module_inject.policies import family_for

        return family_for(path, shape, self.policy)

    def base_spec(self, path: str, shape: Tuple[int, ...]) -> Optional[P]:
        """TP base PartitionSpec for one param (None = replicated)."""
        return self.policy.spec_for(path, tuple(shape), self.tp_size,
                                    self.tp_axis)

    def base_specs(self, params_abstract):
        """Pytree of tp-axis base specs for a whole param tree."""
        from deepspeed_tpu.module_inject.policies import specs_from_policy

        return specs_from_policy(self.policy, params_abstract, self.mesh,
                                 axis=self.tp_axis)

    # -- ZeRO layering ----------------------------------------------------
    def param_spec(self, shape, base_spec=None, stage: int = 3) -> P:
        """Final spec of a parameter under ``stage`` (TP ⊕ ZeRO-3).
        With :attr:`hierarchical_active`, the ZeRO layer spans only the
        non-data axes (hpZ — the per-use gather stays in-replica)."""
        if stage >= 3:
            axes = hierarchical_param_axes(self.zero_axes) \
                if self.hierarchical_active else self.zero_axes
            return zero_partition_spec(
                tuple(shape), self.mesh, data_axes=axes,
                base_spec=base_spec,
                persistence_threshold=self.persistence_threshold)
        return base_spec if base_spec is not None else P()

    def opt_spec(self, shape, base_spec=None, stage: int = 1) -> P:
        """Final spec of an optimizer-state leaf under ``stage``."""
        if stage >= 1:
            return zero_partition_spec(tuple(shape), self.mesh,
                                       data_axes=self.zero_axes,
                                       base_spec=base_spec)
        return base_spec if base_spec is not None else P()

    def shardings(self, params_abstract, stage: int):
        """(param_shardings, opt_shardings) — build_zero_shardings fed
        by this layout's policy/axes/threshold."""
        return build_zero_shardings(
            params_abstract, self.mesh, stage=stage,
            param_specs=self.base_specs(params_abstract),
            persistence_threshold=self.persistence_threshold,
            hierarchical=self.hierarchical_active)

    # -- batch ------------------------------------------------------------
    def batch_spec(self, ndim: int = 2,
                   shape: Optional[Tuple[int, ...]] = None) -> P:
        """Batch arrays: leading dim over ``batch_axes``; with sequence
        parallelism active, dim 1 (tokens) additionally shards over
        ``seq``. Dims not divisible by their axis product stay unsharded
        (requires ``shape``). Never names fsdp/tp (class contract)."""
        from deepspeed_tpu.parallel.topology import axis_spec_entry

        entries = [None] * ndim
        entries[0] = axis_spec_entry(self.mesh, self.batch_axes,
                                     shape[0] if shape is not None else None)
        if ndim >= 2:
            entries[1] = axis_spec_entry(
                self.mesh, (AXIS_SEQ,),
                shape[1] if shape is not None else None)
        return P(*entries)

    def batch_sharding(self, ndim: int = 2,
                       shape: Optional[Tuple[int, ...]] = None
                       ) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(ndim, shape))

    # -- identity ---------------------------------------------------------
    def describe(self) -> Dict:
        """JSON-safe identity of this layout: the axis roles plus one
        canonical spec per parameter family at the live tp size — what
        the docs render and the fingerprint/manifest can embed."""
        tp = self.tp_size
        families = {
            "embedding": spec_entries(P(self.tp_axis, None) if tp > 1
                                      else None),
            "attn_qkv": spec_entries(P(None, self.tp_axis) if tp > 1
                                     else None),
            "attn_proj": spec_entries(P(self.tp_axis, None) if tp > 1
                                      else None),
            "mlp_in": spec_entries(P(None, self.tp_axis) if tp > 1
                                   else None),
            "mlp_out": spec_entries(P(self.tp_axis, None) if tp > 1
                                    else None),
            "norm": spec_entries(None),
        }
        return {
            "policy": getattr(self.policy, "name", "auto"),
            "tp_axis": self.tp_axis,
            "tp_size": tp,
            "zero_axes": list(self.zero_axes),
            "batch_axes": list(self.batch_axes),
            "hierarchical_gather": self.hierarchical_active,
            "families": families,
        }


def default_layout(mesh: Mesh, policy="auto",
                   persistence_threshold: int = 0) -> SpecLayout:
    """The repo-wide default SpecLayout for a mesh (canonical axis
    roles; the knobs engines thread through come from their configs)."""
    return SpecLayout(mesh, policy=policy,
                      persistence_threshold=persistence_threshold)


# ----------------------------------------------------------------------
# PartitionSpec <-> JSON (the topology-manifest wire format: a checkpoint
# must record how every logical tensor was partitioned at save time so a
# restore onto a DIFFERENT mesh can validate and reshard deliberately)
def spec_entries(spec) -> list:
    """JSON-safe form of a PartitionSpec: one entry per dim — ``None``,
    an axis name, or a list of axis names."""
    if spec is None:
        return []
    out = []
    for e in spec:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append([str(a) for a in e])
        else:
            out.append(str(e))
    return out


def sharding_spec_entries(sharding) -> list:
    """JSON-safe partition spec of a (Named)Sharding; fully-replicated /
    unknown sharding kinds serialize as ``[]``."""
    spec = getattr(sharding, "spec", None)
    return spec_entries(spec)


def batch_sharding(mesh: Mesh, data_axes: Optional[Sequence[str]] = None,
                   ndim: int = 2, shape: Optional[Tuple[int, ...]] = None) -> NamedSharding:
    """Batch arrays, per the default :class:`SpecLayout`: leading dim
    over the layout's ``batch_axes`` (data x expert — NEVER fsdp/tp,
    which shard weights); with sequence parallelism active, dim 1
    (tokens) additionally shards over ``seq``. Dims not divisible by
    their axis product stay unsharded (requires ``shape``). An explicit
    ``data_axes`` builds a one-off layout with those batch axes."""
    layout = SpecLayout(mesh) if data_axes is None \
        else SpecLayout(mesh, batch_axes=tuple(data_axes))
    return layout.batch_sharding(ndim=ndim, shape=shape)
