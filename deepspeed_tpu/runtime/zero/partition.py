"""ZeRO stages as GSPMD sharding policies.

The reference implements ZeRO with explicit bookkeeping: flat-buffer
round-robin partitions (``runtime/zero/stage_1_and_2.py:609``), grad-hook IPG
buckets (``:836-942``), and for stage 3 per-param ``ds_tensor`` shards with
gather/release hooks (``runtime/zero/partition_parameters.py:1042``,
``partitioned_param_coordinator.py:239``). On TPU all of that collapses into
*where each array lives on the mesh*:

- **stage 1**: optimizer state (m/v) sharded over the data axes; params
  replicated. XLA's weight-update sharding: grads reduce-scatter into the
  owner shard, updated weights all-gather back — the reference's
  ``allgather_bucket`` loop (``stage_1_and_2.py:1821``) becomes an output
  sharding spec.
- **stage 2**: same program — gradients never materialize replicated because
  the only consumer (the sharded update) needs 1/N of them; XLA's scheduler
  plays the role of the IPG overlap stream.
- **stage 3**: params themselves sharded; every use triggers a (scan-scoped)
  all-gather, every grad a reduce-scatter — the fetch/release coordinator
  becomes dataflow.

Sharding rule: shard the largest dimension divisible by the axis size; params
smaller than ``param_persistence_threshold`` stay replicated (mirrors
``stage3_param_persistence_threshold``).
"""

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import AXIS_DATA, AXIS_EXPERT


def _shardable_dim(shape: Tuple[int, ...], axis_size: int,
                   taken: Sequence[Optional[str]]) -> Optional[int]:
    """Largest dim divisible by axis_size and not already sharded."""
    best, best_size = None, 0
    for i, d in enumerate(shape):
        if taken[i] is None and d % axis_size == 0 and d >= axis_size and d > best_size:
            best, best_size = i, d
    return best


def zero_partition_spec(shape: Tuple[int, ...],
                        mesh: Mesh,
                        data_axes: Sequence[str] = (AXIS_DATA, AXIS_EXPERT),
                        base_spec: Optional[P] = None,
                        persistence_threshold: int = 0) -> P:
    """PartitionSpec sharding ``shape`` over the (flattened) data axes,
    layered on top of ``base_spec`` (TP/expert specs from the model).

    Returns ``base_spec`` unchanged if the array is too small (persistence
    threshold) or no dim divides evenly.
    """
    entries = list(base_spec) if base_spec is not None else []
    entries += [None] * (len(shape) - len(entries))
    used = {a for e in entries for a in (e if isinstance(e, tuple) else (e,)) if a}
    # a mesh axis may appear at most once in a spec: e.g. expert params carry
    # "expert" in their base spec, so ZeRO shards them over "data" only
    data_axes = [a for a in data_axes if mesh.shape.get(a, 1) > 1 and a not in used]
    if not data_axes:
        return base_spec if base_spec is not None else P()
    axis_size = int(np.prod([mesh.shape[a] for a in data_axes]))
    if int(np.prod(shape)) < max(persistence_threshold, axis_size):
        return P(*entries) if base_spec is not None else P()
    dim = _shardable_dim(shape, axis_size, entries)
    if dim is None:
        return P(*entries) if base_spec is not None else P()
    group = tuple(data_axes) if len(data_axes) > 1 else data_axes[0]
    entries[dim] = group
    return P(*entries)


def build_zero_shardings(params_shapes,
                         mesh: Mesh,
                         stage: int,
                         param_specs=None,
                         persistence_threshold: int = 0):
    """Shardings for (params, optimizer state) given a ZeRO stage.

    ``params_shapes``: pytree of ``jax.ShapeDtypeStruct`` (or arrays).
    ``param_specs``: optional pytree of base PartitionSpecs (TP rules).
    Returns ``(param_shardings, opt_shardings)`` pytrees of NamedSharding.
    """

    def base_spec_of(leaf_spec):
        return leaf_spec if leaf_spec is not None else None

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(lambda _: None, params_shapes)

    def param_sharding(leaf, spec):
        base = base_spec_of(spec)
        if stage >= 3:
            s = zero_partition_spec(leaf.shape, mesh,
                                    base_spec=base,
                                    persistence_threshold=persistence_threshold)
        else:
            s = base if base is not None else P()
        return NamedSharding(mesh, s)

    def opt_sharding(leaf, spec):
        base = base_spec_of(spec)
        if stage >= 1:
            s = zero_partition_spec(leaf.shape, mesh, base_spec=base)
        else:
            s = base if base is not None else P()
        return NamedSharding(mesh, s)

    param_shardings = jax.tree_util.tree_map(
        param_sharding, params_shapes, param_specs,
        is_leaf=lambda x: hasattr(x, "shape"))
    opt_shardings = jax.tree_util.tree_map(
        opt_sharding, params_shapes, param_specs,
        is_leaf=lambda x: hasattr(x, "shape"))
    return param_shardings, opt_shardings


def build_opt_state_shardings(opt_abstract, params_abstract, mesh: Mesh,
                              stage: int, param_specs=None):
    """Shardings for an arbitrary optimizer-state pytree.

    Optimizer states are built of (a) subtrees that mirror the params tree
    (Adam m/v, momentum buffers) — those get the per-param ZeRO⊕TP spec —
    and (b) scalars/None — replicated. Subtree matching is structural, so any
    optimizer whose state contains params-shaped pytrees works.
    """
    params_leaves, params_def = jax.tree_util.tree_flatten(params_abstract)
    _, mirrored = build_zero_shardings(params_abstract, mesh, stage=stage,
                                       param_specs=param_specs)
    rep = replicated(mesh)

    def _mirrors_params(sub) -> bool:
        if sub is None:
            return False
        try:
            leaves, treedef = jax.tree_util.tree_flatten(sub)
        except Exception:
            return False
        return (treedef == params_def
                and all(tuple(l.shape) == tuple(p.shape)
                        for l, p in zip(leaves, params_leaves)))

    def handle(sub):
        if _mirrors_params(sub):
            return mirrored
        # lone leaf without a params mirror: shard by its own shape
        if stage >= 1 and getattr(sub, "ndim", 0) > 0:
            return NamedSharding(mesh, zero_partition_spec(tuple(sub.shape), mesh))
        return rep

    # tree_map recursion handles any registered pytree node (FrozenDict,
    # struct dataclasses, ...); is_leaf stops at params-mirroring subtrees
    return jax.tree_util.tree_map(handle, opt_abstract, is_leaf=_mirrors_params)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ----------------------------------------------------------------------
# PartitionSpec <-> JSON (the topology-manifest wire format: a checkpoint
# must record how every logical tensor was partitioned at save time so a
# restore onto a DIFFERENT mesh can validate and reshard deliberately)
def spec_entries(spec) -> list:
    """JSON-safe form of a PartitionSpec: one entry per dim — ``None``,
    an axis name, or a list of axis names."""
    if spec is None:
        return []
    out = []
    for e in spec:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append([str(a) for a in e])
        else:
            out.append(str(e))
    return out


def sharding_spec_entries(sharding) -> list:
    """JSON-safe partition spec of a (Named)Sharding; fully-replicated /
    unknown sharding kinds serialize as ``[]``."""
    spec = getattr(sharding, "spec", None)
    return spec_entries(spec)


def batch_sharding(mesh: Mesh, data_axes: Sequence[str] = (AXIS_DATA, AXIS_EXPERT),
                   ndim: int = 2, shape: Optional[Tuple[int, ...]] = None) -> NamedSharding:
    """Batch arrays: leading dim sharded over the data axes; with sequence
    parallelism active, dim 1 (tokens) additionally shards over ``seq``.
    Dims not divisible by their axis product stay unsharded (requires
    ``shape``)."""
    from deepspeed_tpu.parallel.topology import AXIS_SEQ, axis_spec_entry

    entries = [None] * ndim
    entries[0] = axis_spec_entry(mesh, data_axes,
                                 shape[0] if shape is not None else None)
    if ndim >= 2:
        entries[1] = axis_spec_entry(mesh, (AXIS_SEQ,),
                                     shape[1] if shape is not None else None)
    return NamedSharding(mesh, P(*entries))
