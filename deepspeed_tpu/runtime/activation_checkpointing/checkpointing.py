"""User-facing activation-checkpointing API.

Capability parity with the reference ``deepspeed.checkpointing``
(``runtime/activation_checkpointing/checkpointing.py``): Megatron-style
integrations call ``configure(...)`` once and then wrap segment forwards
in ``checkpoint(fn, *args)``. Here the primitives are TPU-native:

- ``checkpoint`` is ``jax.checkpoint`` (full recompute — the reference's
  CheckpointFunction semantics, ``:498``);
- ``partition_activations`` (ref ``:372``) becomes a GSPMD sharding
  constraint on the tensor args at the checkpoint boundary, so the saved
  copies live model-axis-sharded and gather back at recompute;
- ``checkpoint_in_cpu`` (ref ``:485``): args transfer to HOST memory
  space *before* the remat region and reload to device *inside* it — the
  region's saved residuals are therefore the host-resident copies
  (``jax.checkpoint`` saves its inputs), and backward re-reads host
  memory. XLA's CPU backend cannot execute cross-space placements under
  a mesh (same limitation as the engine's cpu_checkpointing gate) —
  there the flag warns once and is skipped.

This generic-args API intentionally does NOT reuse
``models/remat_utils.py``'s named-value offload policy: that mechanism
addresses values *inside* a model's remat region by ``checkpoint_name``
tags the model code plants; user segments are opaque callables whose
only addressable residuals are their arguments.

The RNG tracker surface (``get_cuda_rng_tracker`` /
``model_parallel_cuda_manual_seed``, ref ``:122-241``) is served with JAX
semantics: explicit fold-in keys per model-parallel rank instead of
mutable device RNG state — counter-based keys replay identically at
recompute by construction.
"""

import contextlib
from typing import Any, Dict

import jax

from deepspeed_tpu.utils.logging import logger

# ---------------------------------------------------------------------
# module configuration (reference module globals, checkpointing.py:830)

_CONFIG: Dict[str, Any] = {
    "partition_activations": False,
    "contiguous_checkpointing": False,
    "checkpoint_in_cpu": False,
    "num_checkpoints": None,
    "synchronize": False,
    "profile": False,
    "configured": False,
}
# knobs XLA makes moot (allocation/scheduling/segment sizing are the
# compiler's): accepted for config parity, warned per configure()
_INERT_KEYS = ("contiguous_checkpointing", "num_checkpoints",
               "synchronize", "profile")
_warned_cpu_backend = False


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference ``configure`` (checkpointing.py:830): explicit kwargs win
    over the ds-config's ``activation_checkpointing`` section. ``mpu_`` is
    accepted for signature parity; the model axis comes from the global
    mesh topology here."""
    del mpu_
    section = {}
    if deepspeed_config is not None:
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        cfg = (deepspeed_config
               if isinstance(deepspeed_config, DeepSpeedConfig)
               else DeepSpeedConfig(deepspeed_config))
        ac = cfg.activation_checkpointing_config
        section = {"partition_activations": ac.partition_activations,
                   "contiguous_checkpointing":
                       ac.contiguous_memory_optimization,
                   "checkpoint_in_cpu": ac.cpu_checkpointing,
                   "num_checkpoints": ac.number_checkpoints,
                   "synchronize": ac.synchronize_checkpoint_boundary,
                   "profile": ac.profile}
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_checkpointing", contiguous_checkpointing),
                     ("checkpoint_in_cpu", checkpoint_in_cpu),
                     ("num_checkpoints", num_checkpoints),
                     ("synchronize", synchronize),
                     ("profile", profile)):
        if val is not None:
            section[key] = val
    _CONFIG.update(section)
    _CONFIG["configured"] = True
    for key in _INERT_KEYS:
        if _CONFIG[key]:
            logger.warning(
                f"deepspeed_tpu.checkpointing: {key} is accepted but INERT "
                "on TPU (XLA owns allocation/scheduling/segment sizing; "
                "use jax.profiler for profiling)")


def is_configured() -> bool:
    return _CONFIG["configured"]


def reset():
    """Reference ``reset`` (checkpointing.py:773)."""
    global _warned_cpu_backend
    _CONFIG.update(partition_activations=False,
                   contiguous_checkpointing=False, checkpoint_in_cpu=False,
                   num_checkpoints=None, synchronize=False, profile=False,
                   configured=False)
    _warned_cpu_backend = False


def partition_activations_in_checkpoint(partition_activation):
    """Reference toggle (checkpointing.py:760)."""
    _CONFIG["partition_activations"] = bool(partition_activation)


def set_num_layers(nlayers):
    """Reference ``set_num_layers`` (checkpointing.py:768) — sized the
    contiguous checkpoint buffers there; INERT here (XLA allocates), kept
    for signature parity and introspection."""
    _CONFIG["num_checkpoints"] = nlayers


# ---------------------------------------------------------------------
# the checkpoint wrapper

def _is_array(x) -> bool:
    return hasattr(x, "dtype") and hasattr(x, "shape") \
        and getattr(x, "ndim", 0) > 0


def _partition_arg(x):
    """Model-axis sharding constraint on a saved arg (the TPU form of the
    reference's partition_activations scatter, checkpointing.py:372).
    Dim choice follows ``models/remat_utils.saved_block_input``: prefer a
    non-leading divisible dim (dim 0 is conventionally the data-sharded
    batch axis — constraining it to the model axis would fight the DP
    layout); fall back to dim 0 only when nothing else divides."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.parallel.topology import AXIS_MODEL, get_topology

    topo = get_topology(create_if_missing=False)
    if topo is None or topo.axis_size(AXIS_MODEL) <= 1 or not _is_array(x):
        return x
    mp = topo.axis_size(AXIS_MODEL)
    for dim in (*range(1, x.ndim), 0):
        if x.shape[dim] % mp == 0:
            spec = [None] * x.ndim
            spec[dim] = AXIS_MODEL
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(topo.mesh, P(*spec)))
    return x


# residual-offload policy for checkpoint_in_cpu: the segment's tensor
# args are tagged with this name inside the region; the policy offloads
# exactly the tagged values to pinned host memory and saves nothing
# else. Same mechanism as the engine's cpu_checkpointing offload policy
# (models/remat_utils.py) — the explicit-device_put formulation was
# rejected by XLA's host offloader on hardware (round 5: it either
# refused the program outright or sank the backward's grad matmul onto
# the host thread as a HostExecute call, with host-CPU numerics).
_CKPT_IN_CPU_NAME = "ds_user_ckpt_in_cpu"


def _ckpt_in_cpu_policy():
    from deepspeed_tpu.models.remat_utils import offload_policy

    return offload_policy(names=(_CKPT_IN_CPU_NAME,))


def checkpoint(function, *args):
    """Reference ``checkpoint(function, *args)`` (checkpointing.py:748):
    run ``function`` under rematerialization — nothing internal is saved;
    the (optionally partitioned / host-resident) args are the segment's
    residuals."""
    global _warned_cpu_backend
    checkpoint_in_cpu = _CONFIG["checkpoint_in_cpu"]
    if checkpoint_in_cpu and jax.default_backend() == "cpu":
        if not _warned_cpu_backend:
            logger.warning(
                "deepspeed_tpu.checkpointing: checkpoint_in_cpu is "
                "unsupported on the CPU backend (no Host placement "
                "execution) — saved activations stay on-device")
            _warned_cpu_backend = True
        checkpoint_in_cpu = False
    if _CONFIG["partition_activations"]:
        args = tuple(_partition_arg(a) for a in args)
    if not checkpoint_in_cpu:
        return jax.checkpoint(function)(*args)
    # host residuals: the tensor args re-enter the region through a
    # checkpoint_name tag, and the offload policy stores exactly those
    # tagged values in pinned host memory for the backward — grads are
    # bit-identical to the on-device remat (verified on hardware)
    from jax.ad_checkpoint import checkpoint_name

    is_arr = [_is_array(a) for a in args]

    def tagged(*as_):
        return function(*(
            checkpoint_name(a, _CKPT_IN_CPU_NAME) if arr else a
            for a, arr in zip(as_, is_arr)))

    return jax.checkpoint(tagged, policy=_ckpt_in_cpu_policy())(*args)


# ---------------------------------------------------------------------
# RNG tracker surface (reference CudaRNGStatesTracker, checkpointing.py:122
# — JAX form: derived keys, no mutable device generator)

class RNGStatesTracker:
    """Named seeds → fold-in derived ``jax.random`` keys.

    The reference swaps CUDA RNG state so each model-parallel rank's
    dropout differs inside checkpointed segments and REPLAYS identically
    at recompute. JAX's counter-based keys give replay for free (the same
    key always produces the same draw); per-rank decorrelation comes from
    folding the mesh-axis index into the key inside sharded code."""

    def __init__(self):
        self._seeds: Dict[str, int] = {}

    def reset(self):
        self._seeds.clear()

    def get_states(self):
        return dict(self._seeds)

    def set_states(self, states):
        self._seeds = dict(states)

    def add(self, name: str, seed: int):
        if name in self._seeds:
            raise ValueError(f"rng state {name!r} already added")
        self._seeds[name] = int(seed)

    @contextlib.contextmanager
    def fork(self, name: str = "model-parallel-rng", fold: int = 0):
        """Yield the derived key for ``name`` (folded by ``fold``, e.g. a
        traced model-parallel rank index). Context-manager form keeps the
        reference's ``with get_cuda_rng_tracker().fork():`` call shape."""
        if name not in self._seeds:
            raise KeyError(f"rng state {name!r} was never add()ed")
        yield jax.random.fold_in(jax.random.PRNGKey(self._seeds[name]),
                                 fold)


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:
    """Reference name kept for drop-in imports (checkpointing.py:193)."""
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed: int):
    """Reference ``model_parallel_cuda_manual_seed`` (checkpointing.py:198):
    registers the data-parallel ('default') and model-parallel seeds. The
    model-parallel seed is offset exactly as the reference does (2718 +
    seed); per-rank decorrelation happens at fold time."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("default", seed)
    _RNG_TRACKER.add("model-parallel-rng", 2718 + int(seed))
