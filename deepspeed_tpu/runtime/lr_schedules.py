"""LR schedules (reference ``deepspeed/runtime/lr_schedules.py``:
``LRRangeTest:308``, ``OneCycle:415``, ``WarmupLR:704``, ``WarmupDecayLR:800``).

Each schedule is a pure ``step -> lr`` callable (jit-compatible: the engine
evaluates it inside the compiled step on the traced step counter), wrapped in
a stateful object exposing the reference's ``step()/get_lr()/state_dict()``
surface for user-loop parity.
"""

import math
from typing import Callable

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


def lr_range_test_fn(lr_range_test_min_lr=1e-3,
                     lr_range_test_step_size=2000,
                     lr_range_test_step_rate=1.0,
                     lr_range_test_staircase=False,
                     **_) -> Callable:
    """Increasing sweep for LR range tests (reference ``LRRangeTest``)."""

    def schedule(step):
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle_fn(cycle_min_lr,
                 cycle_max_lr,
                 cycle_first_step_size=2000,
                 cycle_second_step_size=None,
                 cycle_first_stair_count=0,
                 cycle_second_stair_count=None,
                 decay_step_size=0,
                 decay_lr_rate=0.0,
                 **_) -> Callable:
    """Triangular one-cycle policy (reference ``OneCycle``; momentum cycling
    is a no-op on TPU adam — betas stay config-driven). A positive stair
    count quantizes the corresponding phase into that many discrete lr
    levels (reference staircase mode)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    stairs2 = (cycle_second_stair_count if cycle_second_stair_count
               is not None else cycle_first_stair_count)
    total = cycle_first_step_size + second

    def _quantize(frac, count):
        if count and count > 0:
            return jnp.floor(frac * count) / count
        return frac

    def schedule(step):
        up = _quantize(jnp.minimum(step / cycle_first_step_size, 1.0),
                       cycle_first_stair_count)
        down = _quantize(
            jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0),
            stairs2)
        lr = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (up - down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - total, 0.0) / decay_step_size
            lr = lr / (1.0 + decay_lr_rate * decay_steps)
        return jnp.maximum(lr, 0.0)

    return schedule


def warmup_lr_fn(warmup_min_lr=0.0,
                 warmup_max_lr=0.001,
                 warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE,
                 **_) -> Callable:
    """Warmup then constant (reference ``WarmupLR``)."""
    warmup_num_steps = max(2, warmup_num_steps)

    def schedule(step):
        frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
        if warmup_type == WARMUP_LOG_RATE:
            # log(1+frac*(e-1)) would differ from the reference; it uses
            # log(step+1)/log(N) — replicate that
            frac = jnp.log1p(jnp.minimum(step, warmup_num_steps)) / math.log(warmup_num_steps + 1)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac

    return schedule


def warmup_decay_lr_fn(total_num_steps,
                       warmup_min_lr=0.0,
                       warmup_max_lr=0.001,
                       warmup_num_steps=1000,
                       warmup_type=WARMUP_LOG_RATE,
                       **_) -> Callable:
    """Warmup then linear decay to zero (reference ``WarmupDecayLR``)."""
    warmup = warmup_lr_fn(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_ = max(2, warmup_num_steps)

    def schedule(step):
        lr = warmup(step)
        decay_frac = jnp.clip(
            (total_num_steps - step) / max(1, (total_num_steps - warmup_num_steps_)),
            0.0, 1.0)
        return jnp.where(step <= warmup_num_steps_, lr, warmup_max_lr * decay_frac)

    return schedule


_SCHEDULE_FNS = {
    LR_RANGE_TEST: lr_range_test_fn,
    ONE_CYCLE: one_cycle_fn,
    WARMUP_LR: warmup_lr_fn,
    WARMUP_DECAY_LR: warmup_decay_lr_fn,
}


def get_lr_schedule_fn(name: str, params: dict) -> Callable:
    if name not in _SCHEDULE_FNS:
        raise ValueError(f"Unknown scheduler {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _SCHEDULE_FNS[name](**params)


class LRScheduler:
    """Stateful wrapper with the reference scheduler surface."""

    def __init__(self, schedule_fn: Callable, last_batch_iteration: int = -1):
        self.schedule_fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [float(self.schedule_fn(max(self.last_batch_iteration, 0)))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


# class-style constructors for API parity
def LRRangeTest(optimizer=None, **params):
    return LRScheduler(lr_range_test_fn(**params))


def OneCycle(optimizer=None, **params):
    return LRScheduler(one_cycle_fn(**params))


def WarmupLR(optimizer=None, **params):
    return LRScheduler(warmup_lr_fn(**params))


def WarmupDecayLR(optimizer=None, **params):
    return LRScheduler(warmup_decay_lr_fn(**params))



# ----------------------------------------------------------------------
# CLI tuning-argument helpers (reference ``lr_schedules.py:55-267``): let a
# training script expose the schedule knobs as flags and build the
# ``scheduler`` config section from parsed args. Grouped by the prefix
# each schedule's params share, so the override step is a comprehension
# over the schedule's own arg set rather than a hand-written list per
# schedule.

_TUNING_FLAGS = {
    LR_RANGE_TEST: {
        "lr_range_test_min_lr": (float, 1e-3),
        "lr_range_test_step_rate": (float, 1.0),
        "lr_range_test_step_size": (int, 1000),
        "lr_range_test_staircase": (bool, False),
    },
    ONE_CYCLE: {
        "cycle_first_step_size": (int, 1000),
        "cycle_first_stair_count": (int, -1),
        "cycle_second_step_size": (int, -1),
        "cycle_second_stair_count": (int, -1),
        "decay_step_size": (int, 1000),
        "cycle_min_lr": (float, 0.01),
        "cycle_max_lr": (float, 0.1),
        "decay_lr_rate": (float, 0.0),
        # momentum flags ride along for reference-CLI compatibility;
        # one_cycle_fn documents that momentum cycling is a no-op on
        # TPU adam (betas stay config-driven)
        "cycle_min_mom": (float, 0.8),
        "cycle_max_mom": (float, 0.9),
        "decay_mom_rate": (float, 0.0),
    },
    WARMUP_LR: {
        "warmup_min_lr": (float, 0.0),
        "warmup_max_lr": (float, 0.001),
        "warmup_num_steps": (int, 1000),
        "warmup_type": (str, "log"),
    },
}
# WarmupDecayLR shares WarmupLR's flags plus the total step count
_TUNING_FLAGS[WARMUP_DECAY_LR] = {
    **_TUNING_FLAGS[WARMUP_LR], "total_num_steps": (int, 10_000),
}


def add_tuning_arguments(parser):
    """Add ``--lr_schedule`` + every schedule's flags (reference ``:55``)."""
    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help=f"LR schedule for training; one of "
                            f"{VALID_LR_SCHEDULES}")
    def _str2bool(v):
        return str(v).lower() in ("1", "true", "yes", "on")

    seen = set()
    for flags in _TUNING_FLAGS.values():
        for name, (typ, default) in flags.items():
            if name in seen:
                continue
            seen.add(name)
            group.add_argument(f"--{name}",
                               type=_str2bool if typ is bool else typ,
                               default=default)
    return parser


def parse_arguments(parser=None):
    """Standalone parser over the tuning flags (reference ``:159``).
    Returns ``(lr_sched_args, unknown_args)`` — reference signature; ported
    callers unpack two values."""
    import argparse

    parser = parser or argparse.ArgumentParser()
    add_tuning_arguments(parser)
    args, unknown = parser.parse_known_args()
    return args, unknown


def get_config_from_args(args):
    """``(scheduler_config, error)`` from parsed args (reference ``:248``):
    the config is ``{"type": ..., "params": {...}}`` ready for the
    ``scheduler`` section; ``error`` is a message when ``--lr_schedule``
    is absent or unknown."""
    name = getattr(args, "lr_schedule", None)
    if name is None:
        return None, "--lr_schedule not specified on command line"
    if name not in VALID_LR_SCHEDULES:
        return None, f"{name} is not a supported LR schedule"
    # -1 is the reference's "unset" sentinel ONLY for the flags that
    # default to it (stair counts, second step size)
    sentinels = {k for k, (_, d) in _TUNING_FLAGS[name].items() if d == -1}
    params = {k: getattr(args, k)
              for k in _TUNING_FLAGS[name]
              if hasattr(args, k)
              and not (k in sentinels and getattr(args, k) == -1)}
    return {"type": name, "params": params}, None


def get_lr_from_config(config):
    """``(initial_lr, error)`` for a scheduler config (reference ``:267``):
    a missing ``params`` section is an error, and OneCycle reports
    ``cycle_max_lr`` (the reference's choice — the cycle peak, what a
    range-test consumer wants), not the floor."""
    if "type" not in config:
        return None, "LR schedule type not defined in config"
    if "params" not in config:
        return None, "LR schedule params not defined in config"
    params = config["params"]
    name = config["type"]
    if name == LR_RANGE_TEST:
        return params.get("lr_range_test_min_lr", 1e-3), None
    if name == ONE_CYCLE:
        return params.get("cycle_max_lr", 0.1), None
    if name in (WARMUP_LR, WARMUP_DECAY_LR):
        return params.get("warmup_max_lr", 0.001), None
    return None, f"{name} is not a supported LR schedule"
