"""LR schedules (reference ``deepspeed/runtime/lr_schedules.py``:
``LRRangeTest:308``, ``OneCycle:415``, ``WarmupLR:704``, ``WarmupDecayLR:800``).

Each schedule is a pure ``step -> lr`` callable (jit-compatible: the engine
evaluates it inside the compiled step on the traced step counter), wrapped in
a stateful object exposing the reference's ``step()/get_lr()/state_dict()``
surface for user-loop parity.
"""

import math
from typing import Callable

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


def lr_range_test_fn(lr_range_test_min_lr=1e-3,
                     lr_range_test_step_size=2000,
                     lr_range_test_step_rate=1.0,
                     lr_range_test_staircase=False,
                     **_) -> Callable:
    """Increasing sweep for LR range tests (reference ``LRRangeTest``)."""

    def schedule(step):
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle_fn(cycle_min_lr,
                 cycle_max_lr,
                 cycle_first_step_size=2000,
                 cycle_second_step_size=None,
                 decay_step_size=0,
                 decay_lr_rate=0.0,
                 **_) -> Callable:
    """Triangular one-cycle policy (reference ``OneCycle``; momentum cycling
    is a no-op on TPU adam — betas stay config-driven)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total = cycle_first_step_size + second

    def schedule(step):
        up = jnp.minimum(step / cycle_first_step_size, 1.0)
        down = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        lr = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (up - down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - total, 0.0) / decay_step_size
            lr = lr / (1.0 + decay_lr_rate * decay_steps)
        return jnp.maximum(lr, 0.0)

    return schedule


def warmup_lr_fn(warmup_min_lr=0.0,
                 warmup_max_lr=0.001,
                 warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE,
                 **_) -> Callable:
    """Warmup then constant (reference ``WarmupLR``)."""
    warmup_num_steps = max(2, warmup_num_steps)

    def schedule(step):
        frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
        if warmup_type == WARMUP_LOG_RATE:
            # log(1+frac*(e-1)) would differ from the reference; it uses
            # log(step+1)/log(N) — replicate that
            frac = jnp.log1p(jnp.minimum(step, warmup_num_steps)) / math.log(warmup_num_steps + 1)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac

    return schedule


def warmup_decay_lr_fn(total_num_steps,
                       warmup_min_lr=0.0,
                       warmup_max_lr=0.001,
                       warmup_num_steps=1000,
                       warmup_type=WARMUP_LOG_RATE,
                       **_) -> Callable:
    """Warmup then linear decay to zero (reference ``WarmupDecayLR``)."""
    warmup = warmup_lr_fn(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_ = max(2, warmup_num_steps)

    def schedule(step):
        lr = warmup(step)
        decay_frac = jnp.clip(
            (total_num_steps - step) / max(1, (total_num_steps - warmup_num_steps_)),
            0.0, 1.0)
        return jnp.where(step <= warmup_num_steps_, lr, warmup_max_lr * decay_frac)

    return schedule


_SCHEDULE_FNS = {
    LR_RANGE_TEST: lr_range_test_fn,
    ONE_CYCLE: one_cycle_fn,
    WARMUP_LR: warmup_lr_fn,
    WARMUP_DECAY_LR: warmup_decay_lr_fn,
}


def get_lr_schedule_fn(name: str, params: dict) -> Callable:
    if name not in _SCHEDULE_FNS:
        raise ValueError(f"Unknown scheduler {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _SCHEDULE_FNS[name](**params)


class LRScheduler:
    """Stateful wrapper with the reference scheduler surface."""

    def __init__(self, schedule_fn: Callable, last_batch_iteration: int = -1):
        self.schedule_fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [float(self.schedule_fn(max(self.last_batch_iteration, 0)))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


# class-style constructors for API parity
def LRRangeTest(optimizer=None, **params):
    return LRScheduler(lr_range_test_fn(**params))


def OneCycle(optimizer=None, **params):
    return LRScheduler(one_cycle_fn(**params))


def WarmupLR(optimizer=None, **params):
    return LRScheduler(warmup_lr_fn(**params))


def WarmupDecayLR(optimizer=None, **params):
    return LRScheduler(warmup_decay_lr_fn(**params))


def add_tuning_arguments(parser):
    """Reference CLI tuning args (``lr_schedules.py`` convergence-tuning group)."""
    group = parser.add_argument_group("Convergence Tuning")
    group.add_argument("--lr_schedule", type=str, default=None)
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_second_step_size", type=int, default=None)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_step_size", type=int, default=0)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default=WARMUP_LOG_RATE)
    return parser
