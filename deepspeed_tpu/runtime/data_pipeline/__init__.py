"""Data-efficiency pipeline (reference ``deepspeed/runtime/data_pipeline/``):
curriculum learning, curriculum-aware sampling, random layerwise token drop.
"""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler)

__all__ = ["CurriculumScheduler"]
