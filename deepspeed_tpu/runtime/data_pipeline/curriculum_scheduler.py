"""Curriculum-learning difficulty scheduler.

Capability parity with the reference ``CurriculumScheduler``
(``runtime/data_pipeline/curriculum_scheduler.py:9``): maps the global step
to a difficulty value (typically a sequence length) under the schedules
``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` / ``custom``. The
engine truncates or re-bins batches to the current difficulty; on TPU a
changing seqlen means a new jit specialization, so difficulty steps should
be coarse (``difficulty_step`` rounds to multiples — default 8 keeps shapes
MXU-tile friendly).
"""

from typing import Callable, Dict, Optional

from deepspeed_tpu.utils.logging import logger

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict):
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            if key not in config:
                raise ValueError(f"curriculum learning requires config {key!r}")
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.schedule_type = config["schedule_type"]
        self.schedule_config = dict(config.get("schedule_config", {}))
        self.current_difficulty = self.min_difficulty
        self.first_step = True
        self._custom_fn: Optional[Callable[[int], int]] = None

        if self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            for key in ("total_curriculum_step", "difficulty_step"):
                if key not in self.schedule_config:
                    raise ValueError(
                        f"{self.schedule_type} schedule requires "
                        f"schedule_config {key!r}")
            if int(self.schedule_config["difficulty_step"]) % 8:
                logger.warning(
                    "curriculum difficulty_step not a multiple of 8 — "
                    "seq lengths will fall off MXU tile boundaries")
            if self.schedule_type == FIXED_ROOT:
                self.schedule_config.setdefault("root_degree", 2)
        elif self.schedule_type == FIXED_DISCRETE:
            diff = self.schedule_config.get("difficulty")
            max_step = self.schedule_config.get("max_step")
            if not diff or max_step is None or len(diff) != len(max_step) + 1:
                raise ValueError(
                    "fixed_discrete needs len(difficulty) == len(max_step)+1")
        elif self.schedule_type == CUSTOM:
            pass  # user installs a callable via set_custom_get_difficulty
        else:
            raise ValueError(f"unknown curriculum schedule {self.schedule_type!r}")

    # ------------------------------------------------------------------
    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        self._custom_fn = fn

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def set_current_difficulty(self, difficulty: int):
        self.current_difficulty = int(difficulty)

    def _root_schedule(self, global_steps: int, degree: float) -> int:
        sc = self.schedule_config
        total = int(sc["total_curriculum_step"])
        frac = min(1.0, global_steps / total)
        next_diff = self.min_difficulty + (
            (self.max_difficulty - self.min_difficulty) * frac ** (1.0 / degree))
        step = int(sc["difficulty_step"])
        next_diff = int(next_diff / step) * step
        return min(max(next_diff, self.min_difficulty), self.max_difficulty)

    def get_difficulty(self, global_steps: int) -> int:
        if self.schedule_type == FIXED_LINEAR:
            return self._root_schedule(global_steps, 1.0)
        if self.schedule_type == FIXED_ROOT:
            return self._root_schedule(
                global_steps, float(self.schedule_config["root_degree"]))
        if self.schedule_type == FIXED_DISCRETE:
            diff = self.schedule_config["difficulty"]
            max_step = self.schedule_config["max_step"]
            for d, s in zip(diff, max_step):
                if global_steps <= s:
                    return int(d)
            return int(diff[-1])
        if self._custom_fn is None:
            raise RuntimeError(
                "custom curriculum schedule requires set_custom_get_difficulty")
        return int(self._custom_fn(global_steps))

    def update_difficulty(self, global_steps: int) -> int:
        if self.current_difficulty < self.max_difficulty or self.first_step:
            self.first_step = False
            self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    # state-dict surface (reference parity for checkpointing)
    def state_dict(self):
        return {"current_difficulty": self.current_difficulty,
                "first_step": self.first_step}

    def load_state_dict(self, sd):
        self.current_difficulty = int(sd["current_difficulty"])
        self.first_step = bool(sd.get("first_step", False))
