"""Memory-mapped indexed token dataset (reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` — 645 LoC,
Megatron ``MMapIndexedDataset``).

On-disk format is the standard Megatron "MMIDIDX" layout so corpora
prepared by Megatron/DeepSpeed preprocessing tools load directly:

``<path>.idx``: magic ``MMIDIDX\\x00`` | u64 version=1 | u8 dtype-code |
u64 n_sequences | u64 n_docs | i32 sizes[n] | i64 pointers[n] |
i64 doc_idx[n_docs]
``<path>.bin``: the token arrays, concatenated.

Reads are ``np.memmap`` views — no copies, no RAM proportional to corpus
size. One process feeds the whole TPU mesh (single-controller), so there
is no per-rank file sharding here; the sampler (data_sampler.py) hands out
global batches.
"""

import os
import struct
from typing import List, Optional

import numpy as np

_MAGIC = b"MMIDIDX\x00"

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer (reference ``MMapIndexedDatasetBuilder``)."""

    def __init__(self, out_prefix: str, dtype=np.int32):
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        if self._dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def finalize(self) -> None:
        self._bin.close()
        if self._doc_idx[-1] != len(self._sizes):
            self._doc_idx.append(len(self._sizes))
        sizes = np.asarray(self._sizes, np.int32)
        itemsize = self._dtype.itemsize
        pointers = np.zeros(len(sizes), np.int64)
        np.cumsum(sizes[:-1] * itemsize, out=pointers[1:])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", _DTYPE_CODES[self._dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))


class MMapIndexedDataset:
    """Zero-copy reader. ``ds[i]`` → 1-D numpy view of sequence i."""

    def __init__(self, prefix: str):
        idx_path = index_file_path(prefix)
        with open(idx_path, "rb") as f:
            if f.read(8) != _MAGIC:
                raise ValueError(f"{idx_path}: not an MMIDIDX index")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"{idx_path}: unsupported version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self._dtype = np.dtype(_DTYPES[code])
            (n,) = struct.unpack("<Q", f.read(8))
            (n_docs,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_buf = np.memmap(idx_path, mode="r", order="C")
        self._sizes = np.frombuffer(idx_buf, np.int32, count=n,
                                    offset=offset)
        offset += n * 4
        self._pointers = np.frombuffer(idx_buf, np.int64, count=n,
                                       offset=offset)
        offset += n * 8
        self._doc_idx = np.frombuffer(idx_buf, np.int64, count=n_docs,
                                      offset=offset)
        self._bin = np.memmap(data_file_path(prefix), mode="r", order="C")
        self._prefix = prefix

    def __len__(self) -> int:
        return len(self._sizes)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    @property
    def doc_idx(self) -> np.ndarray:
        return self._doc_idx

    @property
    def dtype(self):
        return self._dtype

    def __getitem__(self, i):
        if isinstance(i, (slice, list, np.ndarray)):
            idxs = (range(*i.indices(len(self))) if isinstance(i, slice)
                    else i)
            return [self[int(j)] for j in idxs]
        if i < 0:
            i += len(self)
        ptr, size = int(self._pointers[i]), int(self._sizes[i])
        return np.frombuffer(self._bin, self._dtype, count=size, offset=ptr)

    def get(self, i: int, offset: int = 0, length: Optional[int] = None):
        """Partial read of sequence i (reference ``get``)."""
        seq = self[i]
        length = length if length is not None else len(seq) - offset
        return seq[offset:offset + length]

    @staticmethod
    def exists(prefix: str) -> bool:
        return (os.path.exists(index_file_path(prefix))
                and os.path.exists(data_file_path(prefix)))
