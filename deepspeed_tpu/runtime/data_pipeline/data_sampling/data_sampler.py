"""Curriculum-aware data sampler (reference ``DeepSpeedDataSampler``,
``runtime/data_pipeline/data_sampling/data_sampler.py:32``).

Yields per-step *global-batch* index lists (micro_batch × dp_world × gas
samples — one engine step's worth; single-controller TPU needs no per-rank
sub-sampling). With curriculum learning enabled, each metric's
``CurriculumScheduler`` gates which samples are eligible: a sample is drawn
only when every metric's difficulty value is within the current threshold
(the reference's cluster-file machinery collapses to in-memory boolean
eligibility over the DataAnalyzer's ``index_to_metric`` maps).

``state_dict``/``load_state_dict`` resume mid-epoch, like the reference.
"""

from typing import Dict, Iterator, List, Optional

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler)
from deepspeed_tpu.utils.logging import logger


class DeepSpeedDataSampler:
    def __init__(self,
                 data_efficiency_config: Dict,
                 one_epoch_total_samples: int,
                 micro_batch_size: int,
                 data_parallel_size: int,
                 gradient_accumulation_steps: int = 1,
                 metric_values: Optional[Dict[str, np.ndarray]] = None,
                 drop_last: bool = True):
        ds_cfg = (data_efficiency_config or {}).get("data_sampling", {})
        self.num_epochs = int(ds_cfg.get("num_epochs", 1))
        self.seed = int((data_efficiency_config or {}).get("seed", 1234))
        self.one_epoch_total_samples = int(one_epoch_total_samples)
        self.total_samples = self.num_epochs * self.one_epoch_total_samples
        self.global_batch_size = (micro_batch_size * data_parallel_size
                                  * gradient_accumulation_steps)
        self.drop_last = drop_last
        self.np_rng = np.random.default_rng(self.seed)
        self.consumed_samples = 0

        # --- curriculum metrics ---
        self.curriculum_schedulers: Dict[str, CurriculumScheduler] = {}
        self.metric_values: Dict[str, np.ndarray] = dict(metric_values or {})
        cl_cfg = ds_cfg.get("curriculum_learning", {})
        self.curriculum_enabled = bool(cl_cfg.get("enabled", False))
        if self.curriculum_enabled:
            metrics = cl_cfg.get("curriculum_metrics", {})
            if not metrics:
                raise ValueError(
                    "curriculum_learning.enabled needs curriculum_metrics")
            for name, mcfg in metrics.items():
                if name not in self.metric_values:
                    raise ValueError(
                        f"curriculum metric {name!r} has no metric_values "
                        "array (run the DataAnalyzer first)")
                if len(self.metric_values[name]) != one_epoch_total_samples:
                    raise ValueError(
                        f"metric {name!r} covers "
                        f"{len(self.metric_values[name])} samples, dataset "
                        f"has {one_epoch_total_samples}")
                self.curriculum_schedulers[name] = CurriculumScheduler(mcfg)
        self.curriculum_step = 0

    # ------------------------------------------------------------------
    def _eligible_indices(self) -> np.ndarray:
        ok = np.ones(self.one_epoch_total_samples, bool)
        for name, sched in self.curriculum_schedulers.items():
            vals = self.metric_values[name]
            # clamp the threshold to each metric's easiest sample so a
            # too-low starting difficulty never empties the pool
            thr = max(sched.get_current_difficulty(), float(vals.min()))
            ok &= vals <= thr
        if not ok.any():
            logger.warning("curriculum eligibility empty (conflicting "
                           "metrics); admitting all samples this step")
            ok[:] = True
        return np.nonzero(ok)[0]

    def get_next_batch(self) -> np.ndarray:
        """Indices for one engine step (global batch)."""
        if self.curriculum_enabled:
            self.curriculum_step += 1
            for sched in self.curriculum_schedulers.values():
                sched.update_difficulty(self.curriculum_step)
            pool = self._eligible_indices()
        else:
            pool = None
        if pool is None:
            batch = self.np_rng.integers(
                0, self.one_epoch_total_samples,
                self.global_batch_size).astype(np.int64)
        else:
            batch = self.np_rng.choice(
                pool, size=self.global_batch_size,
                replace=len(pool) < self.global_batch_size)
        self.consumed_samples += self.global_batch_size
        return batch

    def __iter__(self) -> Iterator[np.ndarray]:
        while self.consumed_samples < self.total_samples:
            yield self.get_next_batch()

    def __len__(self) -> int:
        return self.total_samples // self.global_batch_size

    # ------------------------------------------------------------------
    def state_dict(self) -> Dict:
        return {
            "consumed_samples": self.consumed_samples,
            "curriculum_step": self.curriculum_step,
            "rng_state": self.np_rng.bit_generator.state,
        }

    def load_state_dict(self, sd: Dict) -> None:
        self.consumed_samples = int(sd["consumed_samples"])
        self.curriculum_step = int(sd["curriculum_step"])
        self.np_rng.bit_generator.state = sd["rng_state"]
        for sched in self.curriculum_schedulers.values():
            sched.update_difficulty(self.curriculum_step)

    def current_difficulties(self) -> Dict[str, int]:
        return {n: s.get_current_difficulty()
                for n, s in self.curriculum_schedulers.items()}
