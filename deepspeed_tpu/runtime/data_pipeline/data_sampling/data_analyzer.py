"""Offline per-sample metric analysis (reference ``DataAnalyzer``,
``runtime/data_pipeline/data_sampling/data_analyzer.py:1`` — 527 LoC).

Computes per-sample difficulty metrics over an indexable dataset (e.g. an
:class:`MMapIndexedDataset`) and saves them as plain ``.npy`` maps that
:class:`DeepSpeedDataSampler` consumes for curriculum eligibility. The
reference shards this over ranks and writes cluster files; one TPU host
analyzing with vectorized numpy covers the same corpora without the
machinery — metrics are one int/float per sample.

Built-in metrics: ``seqlen`` (token count) and ``vocab_rarity``
(mean -log frequency of the sample's tokens, reference data-efficiency
paper's metric). Custom metrics are ``name -> fn(sample) -> scalar``.
"""

import os
from typing import Callable, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


def metric_seqlen(sample) -> float:
    return float(len(sample))


class DataAnalyzer:
    def __init__(self, dataset,
                 metric_names=("seqlen",),
                 custom_metrics: Optional[Dict[str, Callable]] = None,
                 save_path: Optional[str] = None):
        self.dataset = dataset
        self.save_path = save_path
        self.metrics: Dict[str, Callable] = {}
        custom = custom_metrics or {}
        for name in metric_names:
            if name == "seqlen":
                self.metrics[name] = metric_seqlen
            elif name == "vocab_rarity":
                self.metrics[name] = None  # two-pass, handled in run()
            elif name in custom:
                self.metrics[name] = custom[name]
            else:
                raise ValueError(f"unknown metric {name!r}")
        for name, fn in custom.items():
            self.metrics.setdefault(name, fn)

    def run(self) -> Dict[str, np.ndarray]:
        n = len(self.dataset)
        out: Dict[str, np.ndarray] = {}
        needs_rarity = any(fn is None for fn in self.metrics.values())
        counts = None
        if needs_rarity:
            counts = {}
            for i in range(n):
                tok, c = np.unique(np.asarray(self.dataset[i]),
                                   return_counts=True)
                for t, cc in zip(tok.tolist(), c.tolist()):
                    counts[t] = counts.get(t, 0) + cc
            total = max(1, sum(counts.values()))
            logf = {t: -np.log(c / total) for t, c in counts.items()}
        for name, fn in self.metrics.items():
            vals = np.zeros(n, np.float64)
            for i in range(n):
                sample = np.asarray(self.dataset[i])
                if fn is None:  # vocab_rarity
                    vals[i] = float(np.mean([logf[int(t)] for t in sample]))
                else:
                    vals[i] = float(fn(sample))
            out[name] = vals
        if self.save_path:
            os.makedirs(self.save_path, exist_ok=True)
            for name, vals in out.items():
                np.save(os.path.join(self.save_path,
                                     f"index_to_metric_{name}.npy"), vals)
            logger.info(f"DataAnalyzer: wrote {len(out)} metric map(s) "
                        f"to {self.save_path}")
        return out

    @staticmethod
    def load(save_path: str) -> Dict[str, np.ndarray]:
        out = {}
        prefix = "index_to_metric_"
        for fname in sorted(os.listdir(save_path)):
            if fname.startswith(prefix) and fname.endswith(".npy"):
                out[fname[len(prefix):-4]] = np.load(
                    os.path.join(save_path, fname))
        return out
