"""Data-efficiency data layer (reference
``runtime/data_pipeline/data_sampling/``): mmap indexed datasets,
curriculum-aware sampling, offline metric analysis."""

from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import (
    DataAnalyzer)
from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_sampler import (
    DeepSpeedDataSampler)
from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)

__all__ = ["DataAnalyzer", "DeepSpeedDataSampler", "MMapIndexedDataset",
           "MMapIndexedDatasetBuilder"]
