"""Random-LTD data routing (reference
``runtime/data_pipeline/data_routing/{scheduler.py,basic_layer.py}``).

The reference wraps transformer layers in ``RandomLayerTokenDrop`` modules
that gather a sampled token subset before the layer and scatter results
back after. The TPU-native form is functional: :func:`apply_random_ltd`
performs gather → layer_fn → scatter with ops from
``deepspeed_tpu.ops.random_ltd``; the scheduler maps global step →
reserved sequence length.
"""

from typing import Callable, Dict

import jax.numpy as jnp

from deepspeed_tpu.ops.random_ltd import (gather_tokens, sample_token_indices,
                                          scatter_tokens)


class RandomLTDScheduler:
    """Reserved-length schedule (reference ``data_routing/scheduler.py``):
    linearly grows the kept-token count from ``start_value`` to the full
    sequence over ``total_layer_token_drop_steps``."""

    def __init__(self, config: Dict):
        ltd = config.get("random_ltd", config)
        sched = ltd.get("random_ltd_schedule", {})
        # reference schedule keys: min_value / max_value / schedule_config
        # {seq_per_step, require_steps} (data_pipeline/constants.py)
        inner = sched.get("schedule_config", {})
        self.start_value = int(sched.get("min_value",
                                         sched.get("start_value", 128)))
        self.max_value = int(sched.get("max_value", ltd.get("max_value", 2048)))
        self.total_steps = int(
            sched.get("total_layer_token_drop_steps",
                      sched.get("total_steps", inner.get("require_steps", 1))))
        self.step_size = int(sched.get("seq_per_step",
                                       inner.get("seq_per_step", 8)))
        self.current_seq = self.start_value
        self.global_steps = 0

    def get_current_seq(self) -> int:
        return self.current_seq

    def update_seq(self, global_steps: int) -> int:
        self.global_steps = global_steps
        frac = min(1.0, global_steps / max(1, self.total_steps))
        seq = self.start_value + (self.max_value - self.start_value) * frac
        seq = int(seq / self.step_size) * self.step_size
        self.current_seq = min(max(seq, self.start_value), self.max_value)
        return self.current_seq

    def state_dict(self):
        return {"current_seq": self.current_seq,
                "global_steps": self.global_steps}

    def load_state_dict(self, sd):
        self.current_seq = int(sd["current_seq"])
        self.global_steps = int(sd.get("global_steps", 0))


def apply_random_ltd(x: jnp.ndarray, rng, reserved_length: int,
                     layer_fn: Callable[[jnp.ndarray], jnp.ndarray],
                     batch_first: bool = True) -> jnp.ndarray:
    """gather → layer → scatter (reference ``RandomLayerTokenDrop.forward``,
    ``data_routing/basic_layer.py:13``). ``layer_fn`` sees only the sampled
    ``reserved_length`` tokens; dropped tokens skip the layer entirely."""
    B, T = (x.shape[0], x.shape[1]) if batch_first else (x.shape[1], x.shape[0])
    if reserved_length >= T:
        return layer_fn(x)
    idx = sample_token_indices(rng, reserved_length, T, B)[0]
    _, gathered = gather_tokens(x, idx, batch_first)
    processed = layer_fn(gathered)
    return scatter_tokens(x, processed, idx, batch_first)
