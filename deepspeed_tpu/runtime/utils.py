"""Reference import-path alias: ``deepspeed.runtime.utils``.

The reference's grab-bag (``deepspeed/runtime/utils.py``) is where users
import ``see_memory_usage`` and the norm helpers from. The real homes
here are :mod:`deepspeed_tpu.utils.memory` and the engine's compiled
clipping path; this module keeps reference-shaped imports working.
"""

from deepspeed_tpu.utils.memory import memory_stats, see_memory_usage


def get_global_norm_of_tensors(tensors, norm_type=2):
    """Global norm over a list/tree of arrays (reference
    ``runtime/utils.py`` ``get_global_norm_of_tensors``). The engine's
    compiled step computes this in-graph (``runtime/engine.py:91``); this
    standalone form serves user code and tooling."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tensors)
    if norm_type == 2:
        return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                            for l in leaves))
    acc = jnp.asarray(0.0, jnp.float32)
    for l in leaves:
        acc = acc + jnp.sum(jnp.abs(l.astype(jnp.float32)) ** norm_type)
    return acc ** (1.0 / norm_type)


def get_global_norm(norm_list):
    """sqrt(sum of squared norms) — reference ``get_global_norm``."""
    import math

    return math.sqrt(sum(float(n) ** 2 for n in norm_list))


def clip_grad_norm_(parameters, max_norm, norm_type=2):
    """Pure clipped-tree form of the reference's in-place
    ``clip_grad_norm_``: returns ``(clipped_tree, total_norm)`` — JAX
    arrays are immutable, so callers rebind instead of mutating."""
    import jax
    import jax.numpy as jnp

    total = get_global_norm_of_tensors(parameters, norm_type)
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return (jax.tree_util.tree_map(
        lambda l: (l.astype(jnp.float32) * scale).astype(l.dtype),
        parameters), total)


__all__ = ["see_memory_usage", "memory_stats", "get_global_norm",
           "get_global_norm_of_tensors", "clip_grad_norm_"]
