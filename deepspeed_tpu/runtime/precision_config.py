"""fp16 / bf16 / amp config sections.

Capability parity with the reference fp16 config parsing in
``deepspeed/runtime/config.py:117-260``. On TPU the default/recommended mixed
precision is bf16 (no loss scaling needed — bf16 has fp32's exponent range);
fp16 with dynamic loss scaling is kept for surface parity.
"""

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = Field(0.0, ge=0.0)  # 0 → dynamic
    initial_scale_power: int = Field(16, ge=0)
    loss_scale_window: int = Field(1000, ge=1)
    hysteresis: int = Field(2, ge=0)
    min_loss_scale: float = Field(1.0, ge=0.0)
    fp16_master_weights_and_grads: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0.0

    @property
    def initial_dynamic_scale(self) -> float:
        return 2.0**self.initial_scale_power if self.dynamic_loss_scale else self.loss_scale


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False


class AMPConfig(DeepSpeedConfigModel):
    """Accepted for parity; on TPU amp == bf16 autocast of matmul inputs."""

    model_config = DeepSpeedConfigModel.model_config.copy()
    model_config["extra"] = "allow"

    enabled: bool = False
