"""Data loading (reference ``deepspeed/runtime/dataloader.py``:
``DeepSpeedDataLoader:39``, ``RepeatingLoader:16``).

TPU-native: one process feeds the whole mesh (single-controller), so the
loader yields *global* batches of ``train_micro_batch_size_per_gpu x
dp_world`` and the engine shards them over the data axes on device_put. On
multi-host pods each process loads its slice and the engine assembles a
global array (``make_array_from_process_local_data``).
"""

from typing import Any, Callable, Iterable, Optional

import numpy as np


def dataset_len(dataset) -> int:
    """Sample count of a dataset in any accepted shape: tuple → columns of
    arrays, dict → column mapping, else ``len`` (samples)."""
    if isinstance(dataset, tuple):
        return len(dataset[0])
    if isinstance(dataset, dict):
        return len(next(iter(dataset.values())))
    return len(dataset)


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference ``:16``)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batched loader over an indexable dataset.

    ``dataset`` may be: a numpy array / jax array (first dim = samples), a
    tuple/dict of such arrays, or any object with ``__len__`` +
    ``__getitem__``. ``collate_fn`` assembles a batch from a list of samples
    (defaults to np.stack per leaf for array-like samples).
    """

    def __init__(self,
                 dataset,
                 batch_size: int,
                 local_rank: int = 0,
                 collate_fn: Optional[Callable] = None,
                 num_local_io_workers: Optional[int] = None,
                 data_sampler=None,
                 data_parallel_world_size: Optional[int] = None,
                 data_parallel_rank: Optional[int] = None,
                 dataloader_drop_last: bool = False,
                 shuffle: bool = False,
                 seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.drop_last = dataloader_drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.data_sampler = data_sampler
        self.epoch = 0
        self._len = self._num_batches()

    def _dataset_len(self) -> int:
        return dataset_len(self.dataset)

    def _num_batches(self) -> int:
        n = self._dataset_len()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __len__(self):
        if self.data_sampler is not None and hasattr(self.data_sampler,
                                                     "global_batch_size"):
            # sampler drives the schedule: len(sampler) global batches of
            # global_batch_size samples, rebatched to this loader's size
            total = len(self.data_sampler) * self.data_sampler.global_batch_size
            return (total // self.batch_size if self.drop_last
                    else -(-total // self.batch_size))
        return self._len

    def _index(self, idx):
        d = self.dataset
        if isinstance(d, tuple):
            return tuple(x[idx] for x in d)
        if isinstance(d, dict):
            return {k: v[idx] for k, v in d.items()}
        return d[idx]

    def _samplewise(self) -> bool:
        """True when the dataset yields one sample per __getitem__ (lists,
        MMapIndexedDataset, generic map-style datasets) rather than
        supporting fancy array indexing. Array-likes have BOTH dtype and
        shape; an indexed dataset exposes dtype alone."""
        return isinstance(self.dataset, list) or not (
            isinstance(self.dataset, (np.ndarray, tuple, dict))
            or (hasattr(self.dataset, "dtype")
                and hasattr(self.dataset, "shape")))

    def _yield_batch(self, idx):
        if self._samplewise():
            samples = [self.dataset[int(i)] for i in idx]
            if self.collate_fn is not None:
                return self.collate_fn(samples)
            return _default_collate(samples)
        return self._index(idx)

    def __iter__(self):
        if self.data_sampler is not None:
            # sampler drives the index stream; it may yield single indices
            # or whole index arrays (DeepSpeedDataSampler yields one global
            # batch per engine step) — rebatch to the loader's batch_size.
            # NOTE: a stateful sampler (consumed_samples) spans its whole
            # num_epochs budget across __iter__ calls and is single-pass;
            # iterating past exhaustion yields nothing, loudly:
            if (hasattr(self.data_sampler, "consumed_samples")
                    and hasattr(self.data_sampler, "total_samples")
                    and self.data_sampler.consumed_samples
                    >= self.data_sampler.total_samples):
                from deepspeed_tpu.utils.logging import logger

                logger.warning(
                    "data sampler exhausted (consumed "
                    f"{self.data_sampler.consumed_samples}/"
                    f"{self.data_sampler.total_samples} samples); this "
                    "iteration yields no batches — raise "
                    "data_sampling.num_epochs or rebuild the sampler")
            buf = np.empty((0,), np.int64)
            for chunk in iter(self.data_sampler):
                buf = np.concatenate(
                    [buf, np.atleast_1d(np.asarray(chunk, np.int64))])
                while len(buf) >= self.batch_size:
                    idx, buf = buf[:self.batch_size], buf[self.batch_size:]
                    yield self._yield_batch(idx)
            if len(buf) and not self.drop_last:
                yield self._yield_batch(buf)
            return
        n = self._dataset_len()
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        self.epoch += 1
        nb = self._len
        for b in range(nb):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                return
            yield self._yield_batch(idx)


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(np.stack([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    return np.stack(samples)
