"""Data loading (reference ``deepspeed/runtime/dataloader.py``:
``DeepSpeedDataLoader:39``, ``RepeatingLoader:16``).

TPU-native: one process feeds the whole mesh (single-controller), so the
loader yields *global* batches of ``train_micro_batch_size_per_gpu x
dp_world`` and the engine shards them over the data axes on device_put. On
multi-host pods each process loads its slice and the engine assembles a
global array (``make_array_from_process_local_data``).
"""

from typing import Any, Callable, Iterable, Optional

import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference ``:16``)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batched loader over an indexable dataset.

    ``dataset`` may be: a numpy array / jax array (first dim = samples), a
    tuple/dict of such arrays, or any object with ``__len__`` +
    ``__getitem__``. ``collate_fn`` assembles a batch from a list of samples
    (defaults to np.stack per leaf for array-like samples).
    """

    def __init__(self,
                 dataset,
                 batch_size: int,
                 local_rank: int = 0,
                 collate_fn: Optional[Callable] = None,
                 num_local_io_workers: Optional[int] = None,
                 data_sampler=None,
                 data_parallel_world_size: Optional[int] = None,
                 data_parallel_rank: Optional[int] = None,
                 dataloader_drop_last: bool = False,
                 shuffle: bool = False,
                 seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.drop_last = dataloader_drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.data_sampler = data_sampler
        self.epoch = 0
        self._len = self._num_batches()

    def _dataset_len(self) -> int:
        # tuple → columns of arrays; list → list of samples (torch-style)
        if isinstance(self.dataset, tuple):
            return len(self.dataset[0])
        if isinstance(self.dataset, dict):
            return len(next(iter(self.dataset.values())))
        return len(self.dataset)

    def _num_batches(self) -> int:
        n = self._dataset_len()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __len__(self):
        return self._len

    def _index(self, idx):
        d = self.dataset
        if isinstance(d, tuple):
            return tuple(x[idx] for x in d)
        if isinstance(d, dict):
            return {k: v[idx] for k, v in d.items()}
        return d[idx]

    def _samplewise(self) -> bool:
        """True when the dataset yields one sample per __getitem__ (lists and
        generic map-style datasets) rather than supporting fancy indexing."""
        return isinstance(self.dataset, list) or not (
            isinstance(self.dataset, (np.ndarray, tuple, dict))
            or hasattr(self.dataset, "dtype"))

    def __iter__(self):
        n = self._dataset_len()
        order = np.arange(n)
        if self.data_sampler is not None:
            order = np.fromiter(iter(self.data_sampler), dtype=np.int64)
        elif self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        self.epoch += 1
        nb = self._len
        for b in range(nb):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                return
            if self._samplewise():
                samples = [self.dataset[int(i)] for i in idx]
                if self.collate_fn is not None:
                    yield self.collate_fn(samples)
                else:
                    yield _default_collate(samples)
            else:
                yield self._index(idx)


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(np.stack([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    return np.stack(samples)
