"""Data loading (reference ``deepspeed/runtime/dataloader.py``:
``DeepSpeedDataLoader:39``, ``RepeatingLoader:16``).

TPU-native: one process feeds the whole mesh (single-controller), so the
loader yields *global* batches of ``train_micro_batch_size_per_gpu x
dp_world`` and the engine shards them over the data axes on device_put. On
multi-host pods each process loads its slice and the engine assembles a
global array (``make_array_from_process_local_data``).
"""

from typing import Any, Callable, Iterable, Optional

import numpy as np


def dataset_len(dataset) -> int:
    """Sample count of a dataset in any accepted shape: tuple → columns of
    arrays, dict → column mapping, else ``len`` (samples)."""
    if isinstance(dataset, tuple):
        return len(dataset[0])
    if isinstance(dataset, dict):
        return len(next(iter(dataset.values())))
    return len(dataset)


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference ``:16``).

    State (cursor + RNG seed) passes through to the wrapped loader when
    it is state-capable (:class:`DeepSpeedDataLoader`), so an elastic
    resume restores the exact sample position through the wrapper."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    # cursor API provided via __getattr__ (not plain methods) so that
    # ``hasattr(wrapper, "load_state_dict")`` is False when the wrapped
    # loader is a plain iterable — capability probes in the elastic
    # restore must see the wrapper exactly as capable as what it wraps,
    # or the designed micro-batch fast-forward fallback is unreachable
    def __getattr__(self, name):
        if name in ("state_dict", "load_state_dict",
                    "fast_forward_samples"):
            inner = getattr(self.loader, name)  # AttributeError -> hasattr False
            if name == "state_dict":
                return inner

            def call(*args, **kwargs):
                out = inner(*args, **kwargs)
                # the live iterator predates the cursor restore; rebuild
                # so the next __next__ starts at the restored position
                self.data_iter = iter(self.loader)
                return out

            return call
        raise AttributeError(name)


class DeepSpeedDataLoader:
    """Batched loader over an indexable dataset.

    ``dataset`` may be: a numpy array / jax array (first dim = samples), a
    tuple/dict of such arrays, or any object with ``__len__`` +
    ``__getitem__``. ``collate_fn`` assembles a batch from a list of samples
    (defaults to np.stack per leaf for array-like samples).
    """

    def __init__(self,
                 dataset,
                 batch_size: int,
                 local_rank: int = 0,
                 collate_fn: Optional[Callable] = None,
                 num_local_io_workers: Optional[int] = None,
                 data_sampler=None,
                 data_parallel_world_size: Optional[int] = None,
                 data_parallel_rank: Optional[int] = None,
                 dataloader_drop_last: bool = False,
                 shuffle: bool = False,
                 seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.drop_last = dataloader_drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.data_sampler = data_sampler
        self.epoch = 0
        # sample cursor: which epoch's permutation is being consumed and
        # how many samples of it have been yielded — together with the
        # (seed-derived, deterministic) per-epoch order this pins the
        # exact position in the GLOBAL sample sequence, independent of
        # batch size (the elastic-resume replay anchor)
        self._cursor_epoch = 0
        self._cursor_offset = 0
        self._resume_offset = 0
        self._len = self._num_batches()

    def _dataset_len(self) -> int:
        return dataset_len(self.dataset)

    def _num_batches(self) -> int:
        n = self._dataset_len()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __len__(self):
        if self.data_sampler is not None and hasattr(self.data_sampler,
                                                     "global_batch_size"):
            # sampler drives the schedule: len(sampler) global batches of
            # global_batch_size samples, rebatched to this loader's size
            total = len(self.data_sampler) * self.data_sampler.global_batch_size
            return (total // self.batch_size if self.drop_last
                    else -(-total // self.batch_size))
        return self._len

    def _index(self, idx):
        d = self.dataset
        if isinstance(d, tuple):
            return tuple(x[idx] for x in d)
        if isinstance(d, dict):
            return {k: v[idx] for k, v in d.items()}
        return d[idx]

    def _samplewise(self) -> bool:
        """True when the dataset yields one sample per __getitem__ (lists,
        MMapIndexedDataset, generic map-style datasets) rather than
        supporting fancy array indexing. Array-likes have BOTH dtype and
        shape; an indexed dataset exposes dtype alone."""
        return isinstance(self.dataset, list) or not (
            isinstance(self.dataset, (np.ndarray, tuple, dict))
            or (hasattr(self.dataset, "dtype")
                and hasattr(self.dataset, "shape")))

    def _yield_batch(self, idx):
        if self._samplewise():
            samples = [self.dataset[int(i)] for i in idx]
            if self.collate_fn is not None:
                return self.collate_fn(samples)
            return _default_collate(samples)
        return self._index(idx)

    def __iter__(self):
        if self.data_sampler is not None:
            # sampler drives the index stream; it may yield single indices
            # or whole index arrays (DeepSpeedDataSampler yields one global
            # batch per engine step) — rebatch to the loader's batch_size.
            # NOTE: a stateful sampler (consumed_samples) spans its whole
            # num_epochs budget across __iter__ calls and is single-pass;
            # iterating past exhaustion yields nothing, loudly:
            if (hasattr(self.data_sampler, "consumed_samples")
                    and hasattr(self.data_sampler, "total_samples")
                    and self.data_sampler.consumed_samples
                    >= self.data_sampler.total_samples):
                from deepspeed_tpu.utils.logging import logger

                logger.warning(
                    "data sampler exhausted (consumed "
                    f"{self.data_sampler.consumed_samples}/"
                    f"{self.data_sampler.total_samples} samples); this "
                    "iteration yields no batches — raise "
                    "data_sampling.num_epochs or rebuild the sampler")
            buf = np.empty((0,), np.int64)
            for chunk in iter(self.data_sampler):
                buf = np.concatenate(
                    [buf, np.atleast_1d(np.asarray(chunk, np.int64))])
                while len(buf) >= self.batch_size:
                    idx, buf = buf[:self.batch_size], buf[self.batch_size:]
                    yield self._yield_batch(idx)
            if len(buf) and not self.drop_last:
                yield self._yield_batch(buf)
            return
        n = self._dataset_len()
        order = np.arange(n)
        epoch = self.epoch
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        self.epoch += 1
        # resume support: start this pass partway into the epoch's order
        # (set by load_state_dict / fast_forward_samples); consumed once
        start = self._resume_offset
        self._resume_offset = 0
        self._cursor_epoch = epoch
        self._cursor_offset = start
        pos = start
        while pos < n:
            idx = order[pos:pos + self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                return
            pos += len(idx)
            self._cursor_offset = pos
            yield self._yield_batch(idx)


    # ------------------------------------------------------------------
    # sample-exact cursor (elastic resume / rollback replay)
    def _check_sampler_resumable(self, what: str):
        """A custom ``data_sampler`` drives its own sample order, so the
        epoch/offset cursor does not describe it; its position is only
        capturable through a ``consumed_samples`` attribute (the stateful
        curriculum samplers). Anything else must fail LOUDLY — a cursor
        that silently records/restores nothing would restart the stream
        from the beginning, the exact failure sample-exact replay exists
        to prevent (the engine's manifest writer degrades to
        no-cursor-recorded on this error)."""
        if (self.data_sampler is not None
                and not hasattr(self.data_sampler, "consumed_samples")):
            raise ValueError(
                f"cannot {what}: data_sampler "
                f"{type(self.data_sampler).__name__} exposes no "
                "consumed_samples, so its position in the sample stream "
                "is unknowable — sample-exact elastic resume is not "
                "supported for this sampler")

    def state_dict(self) -> dict:
        """Position in the global sample sequence + the RNG identity that
        makes each epoch's order reproducible. Batch-size independent:
        a resumed loader with a DIFFERENT batch size continues the exact
        sample stream (the elastic topology-shift contract). With a
        stateful ``data_sampler`` the position lives in its
        ``consumed_samples`` (the epoch/offset cursor describes only the
        sampler-less index order)."""
        self._check_sampler_resumable("snapshot the cursor")
        state = {
            "epoch": int(self._cursor_epoch),
            "offset": int(self._cursor_offset),
            "seed": int(self.seed),
            "shuffle": bool(self.shuffle),
            "dataset_len": int(self._dataset_len()),
        }
        if self.data_sampler is not None:
            consumed = getattr(self.data_sampler, "consumed_samples", None)
            if consumed is not None:
                state["sampler_consumed_samples"] = int(consumed)
        return state

    def load_state_dict(self, state: dict):
        """Restore the cursor. Loud on identity mismatches: a different
        seed/shuffle/dataset length would silently change which samples
        each step sees — the exact failure sample-exact replay exists to
        prevent."""
        self._check_sampler_resumable("restore the cursor")
        for field, mine in (("seed", self.seed), ("shuffle", self.shuffle),
                            ("dataset_len", self._dataset_len())):
            theirs = state.get(field)
            if theirs is not None and theirs != mine:
                raise ValueError(
                    f"dataloader state mismatch: saved {field}="
                    f"{theirs!r} but this loader has {field}={mine!r} — "
                    "an elastic resume must rebuild the loader with the "
                    "same dataset/seed/shuffle so the global sample "
                    "sequence continues exactly")
        epoch, offset = int(state["epoch"]), int(state["offset"])
        n = self._dataset_len()
        if n > 0 and offset >= n:
            epoch += offset // n
            offset = offset % n
        self.epoch = epoch
        self._cursor_epoch = epoch
        self._cursor_offset = offset
        self._resume_offset = offset
        consumed = state.get("sampler_consumed_samples")
        if (consumed is not None and self.data_sampler is not None
                and hasattr(self.data_sampler, "consumed_samples")):
            self.data_sampler.consumed_samples = int(consumed)

    def fast_forward_samples(self, n_samples: int):
        """Seek to global sample index ``n_samples`` (the engine's
        ``global_samples`` counter) — the manifest-less fallback when no
        saved cursor is available. With ``drop_last`` the per-epoch
        yielded count depends on batch size, so cursor state
        (:meth:`state_dict`) is the exact mechanism; this seek assumes
        the historical batch geometry yielded full epochs."""
        n = self._dataset_len()
        per_epoch = ((n // self.batch_size) * self.batch_size
                     if self.drop_last else n)
        if per_epoch <= 0:
            raise ValueError(
                f"cannot fast-forward: dataset of {n} sample(s) yields no "
                f"full batch at batch_size={self.batch_size} with "
                "drop_last")
        self.load_state_dict({
            "epoch": int(n_samples) // per_epoch,
            "offset": int(n_samples) % per_epoch,
            "seed": self.seed, "shuffle": self.shuffle, "dataset_len": n})


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(np.stack([s[i] for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    return np.stack(samples)
