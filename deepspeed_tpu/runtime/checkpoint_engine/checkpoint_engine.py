"""Checkpoint engine abstraction (reference
``runtime/checkpoint_engine/checkpoint_engine.py``: pluggable
save/load/commit used by the engine; Torch and Nebula impls).

Implementations here:
- :class:`ArrayCheckpointEngine` — synchronous npz+json format (the
  ``TorchCheckpointEngine`` equivalent).
- :class:`OrbaxCheckpointEngine` — async sharded checkpointing via orbax
  (the Nebula-equivalent async tier), used when ``checkpoint.async_save``.
"""

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def fsync_dir(path):
    """Make rename/creation of directory entries durable (fsyncing file
    contents alone does not persist the dirent on ext4/xfs). Shared by
    the tiered engine's atomic publish and the resilience layer's
    crash-safe pointer/manifest writes."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # platform without dir-fsync: best effort
        pass


def atomic_write_bytes(path: str, blob: bytes):
    """tmp file + fsync + ``os.replace``: a crash mid-write can never
    leave a truncated file at ``path`` — either the old content survives
    or the new content is complete. THE durable-write primitive (one
    implementation on purpose — the crash-safety sequence must not fork):
    AOT program blobs directly, sidecar manifests and the resilience
    layer's pointer/manifest/registry writes via
    :func:`atomic_write_text`."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def atomic_write_text(path: str, text: str):
    """Text face of :func:`atomic_write_bytes` (re-exported from
    ``resilience.integrity``)."""
    atomic_write_bytes(path, text.encode())


class CheckpointEngine:
    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        log_dist(f"[ckpt] Saving checkpoint: {tag}", ranks=[0])

    def save(self, state_dict: Dict[str, Any], path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        raise NotImplementedError

    def save_text(self, path: str, text: str):
        """Small sidecar metadata file saved into a tag directory (the
        topology manifest). Atomic (:func:`atomic_write_text`) so a
        crash mid-write never leaves a truncated record; staging-capable
        engines override this so the sidecar rides their atomic
        publish."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        atomic_write_text(path, text)

    def save_bytes(self, path: str, blob: bytes):
        """Binary sidecar saved into a tag directory (the AOT program
        bundle's executable blobs). Same atomicity and staging contract
        as :meth:`save_text`."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        atomic_write_bytes(path, blob)

    def commit(self, tag):
        return True

    def makedirs(self, path, exist_ok=False):
        os.makedirs(path, exist_ok=exist_ok)


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    """Flatten nested dict/tuple/list/namedtuple structure to {path: leaf}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif hasattr(tree, "_fields"):  # namedtuple
        for k in tree._fields:
            out.update(_flatten(getattr(tree, k), f"{prefix}{k}/"))
    elif isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is None:
        out[prefix.rstrip("/") + "#none"] = None
    else:
        out[prefix.rstrip("/")] = tree
    return out


class LazyNpz:
    """Slice-addressable reader over an uncompressed ``.npz``.

    ``np.savez`` stores each member with ``ZIP_STORED`` (no compression),
    so every array's bytes sit contiguously in the archive at a knowable
    offset. This reader parses the zip + npy headers ONCE, then serves
    ``read_slice(key, index)`` through a per-call ``np.memmap`` — only
    the pages the slice touches are read. That is what lets the
    reshard-at-load path materialize an M-way-sharded tensor from an
    N-way-era checkpoint without any host reading the full file
    (``jax.make_array_from_callback`` asks for exactly this host's shard
    indices). Compressed/Fortran/object members degrade to a cached
    full read of that member only.
    """

    def __init__(self, path: str):
        import struct
        import zipfile

        self._path = path
        # key -> (array_byte_offset, shape, dtype) | None (full-read fallback)
        self._entries: Dict[str, Optional[tuple]] = {}
        self._full_cache: Dict[str, Any] = {}
        with zipfile.ZipFile(path) as zf, open(path, "rb") as raw:
            for zinfo in zf.infolist():
                name = zinfo.filename
                key = name[:-4] if name.endswith(".npy") else name
                entry = None
                if zinfo.compress_type == zipfile.ZIP_STORED:
                    raw.seek(zinfo.header_offset)
                    local = raw.read(30)
                    if len(local) == 30 and local[:4] == b"PK\x03\x04":
                        nlen, elen = struct.unpack("<HH", local[26:30])
                        raw.seek(zinfo.header_offset + 30 + nlen + elen)
                        entry = self._parse_npy_header(raw)
                self._entries[key] = entry

    @staticmethod
    def _parse_npy_header(f):
        try:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
            elif version == (2, 0):
                shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
            else:
                return None
        except ValueError:
            return None
        if fortran or dtype.hasobject:
            return None
        return (f.tell(), tuple(shape), dtype)

    def keys(self):
        return list(self._entries)

    def __contains__(self, key):
        return key in self._entries

    def shape_dtype(self, key):
        entry = self._entries[key]
        if entry is not None:
            return entry[1], entry[2]
        a = self._full(key)
        return tuple(a.shape), a.dtype

    def _full(self, key):
        if key not in self._full_cache:
            with np.load(self._path, allow_pickle=False) as z:
                self._full_cache[key] = z[key]
        return self._full_cache[key]

    def read_slice(self, key: str, index=()) -> np.ndarray:
        """Materialize ``arr[index]`` reading only those bytes (plus
        filesystem page granularity). ``index`` is a tuple of slices —
        exactly what ``jax.make_array_from_callback`` hands its
        callback; ``()`` reads the whole array."""
        entry = self._entries[key]
        if entry is None:
            return np.ascontiguousarray(self._full(key)[index])
        offset, shape, dtype = entry
        if not shape:  # 0-d member
            mm = np.memmap(self._path, dtype=dtype, mode="r",
                           offset=offset, shape=(1,))
            return np.asarray(mm[0]).reshape(())
        mm = np.memmap(self._path, dtype=dtype, mode="r",
                       offset=offset, shape=shape)
        return np.array(mm[index])  # copy: touches only the sliced pages

    def read(self, key: str) -> np.ndarray:
        return self.read_slice(key, ())


def apply_npz_meta(flat: Dict[str, Any], meta: Dict[str, Any]) -> Dict[str, Any]:
    """Decode the ``.json`` sidecar's markers over loaded npz payloads,
    in place: ``#none`` entries restore None leaves, ``#dtype`` entries
    re-view uint payloads back to their ml_dtypes type, everything else
    is a scalar/string leaf. The single owner of the sidecar marker
    semantics — regular loads and the reshard-at-load path must decode
    identically."""
    for k, v in meta.items():
        if k.endswith("#none"):
            flat[k[:-len("#none")]] = None
        elif k.endswith("#dtype"):
            import ml_dtypes  # noqa: F401 — registers the names

            base = k[:-len("#dtype")]
            if base in flat:
                flat[base] = flat[base].view(np.dtype(v))
        else:
            flat[k] = v
    return flat


class ArrayCheckpointEngine(CheckpointEngine):
    """npz (arrays) + json (structure/scalars) on the filesystem.

    ``save`` expects a dict whose leaves are arrays / python scalars /
    strings; arbitrary nesting (incl. namedtuples) is flattened with
    path-keys, so ``load`` returns a flat ``{path: value}`` mapping plus the
    original metadata — the engine re-assembles pytrees from its own treedef.
    """

    def save(self, state_dict: Dict[str, Any], path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        flat = _flatten(state_dict)
        arrays, meta = {}, {}
        for k, v in flat.items():
            if k.endswith("#none"):
                meta[k] = None
            elif hasattr(v, "shape"):
                a = np.asarray(v)
                if a.dtype.kind not in "biufcSU?":
                    # npz silently stores ml_dtypes (bfloat16, float8_*)
                    # as raw void — a bf16 leaf would round-trip as |V2.
                    # Store a same-width uint view + the dtype name.
                    meta[k + "#dtype"] = str(v.dtype)
                    a = a.view({1: np.uint8, 2: np.uint16,
                                4: np.uint32}[a.dtype.itemsize])
                arrays[k] = a
            else:
                meta[k] = v
        np.savez(path + ".npz", **arrays)
        with open(path + ".json", "w") as f:
            json.dump(meta, f, default=str)

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        flat = {}
        with np.load(path + ".npz", allow_pickle=False) as z:
            for k in z.files:
                flat[k] = z[k]
        if os.path.exists(path + ".json"):
            with open(path + ".json") as f:
                meta = json.load(f)
            apply_npz_meta(flat, meta)
        return flat

    supports_lazy = True

    def load_lazy(self, path: str):
        """``(LazyNpz, meta)`` pair for slice-addressable reads: the
        reshard-at-load path pulls only the slices the current mesh's
        shards need. ``meta`` is the raw sidecar json (``#none`` /
        ``#dtype`` markers included — the caller applies them, since a
        sliced payload must be dtype-viewed AFTER slicing)."""
        reader = LazyNpz(path + ".npz")
        meta: Dict[str, Any] = {}
        if os.path.exists(path + ".json"):
            with open(path + ".json") as f:
                meta = json.load(f)
        return reader, meta


class OrbaxCheckpointEngine(CheckpointEngine):
    """Async sharded checkpointing (orbax) — the reference's Nebula slot
    (``nebula_checkpoint_engine.py:15``): commit() waits for the async save."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._manager = None
        self._pending = []

    def save(self, state_dict, path):
        ckptr = self._ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path) + ".orbax", state_dict, force=True)
        self._pending.append(ckptr)

    def load(self, path, map_location=None):
        ckptr = self._ocp.StandardCheckpointer()
        return ckptr.restore(os.path.abspath(path) + ".orbax")

    def commit(self, tag):
        for c in self._pending:
            c.wait_until_finished()
        self._pending.clear()
        return True


class ShardedCheckpointEngine(OrbaxCheckpointEngine):
    """Sharded checkpointing WITHOUT consolidation.

    The engine hands this checkpointer its live sharded ``jax.Array`` trees;
    orbax/tensorstore writes only each host's addressable shards, in
    parallel across hosts, and restore re-shards onto whatever mesh the
    loading engine runs — the universal-checkpoint capability (reference
    ``checkpoint/universal_checkpoint.py:13``, ZeRO elastic reshaping
    ``stage_1_and_2.py:2131``) as a storage-layer property instead of
    offline reshape scripts. ``save`` must be called by ALL processes
    (collective); ``commit`` barriers on write completion (the reference's
    tag-commit semantics, ``engine.py:3043``).
    """

    supports_sharded = True

    def load_sharded(self, path, abstract_tree):
        """Restore onto the shardings carried by ``abstract_tree`` leaves
        (jax.ShapeDtypeStruct with ``.sharding`` set): each host reads only
        the byte ranges its shards need."""
        ckptr = self._ocp.StandardCheckpointer()
        return ckptr.restore(os.path.abspath(path) + ".orbax",
                             target=abstract_tree)


class TieredCheckpointEngine(CheckpointEngine):
    """Service-style commit/rollback checkpointing — the reference's Nebula
    slot (``nebula/`` + ``nebula_checkpoint_engine.py:15``), rebuilt
    service-free:

    - ``save`` writes into a hidden per-tag STAGING dir, never the final
      location — a crash mid-save leaves no partial checkpoint visible;
    - ``commit`` fsyncs and atomically publishes (``os.replace``) staging
      into the tag dir, then mirrors to durable storage
      (``persistent_storage_path``) at most every
      ``persistent_time_interval`` seconds and prunes mirror versions
      beyond ``num_of_version_in_retention`` (only versions this engine
      published — recorded in a manifest — are ever pruned);
    - uncommitted staging from a crashed run is rolled back on the next
      ``create``;
    - ``load`` falls back to the durable mirror when the fast tier lost
      the file (the recovery path Nebula's service provides).
    """

    def __init__(self, config_params=None, inner: CheckpointEngine = None):
        super().__init__(config_params)
        cfg = config_params
        self._inner = inner or ArrayCheckpointEngine()
        self._persist_path = getattr(cfg, "persistent_storage_path", None)
        self._persist_interval = float(
            getattr(cfg, "persistent_time_interval", 100.0))
        self._retention = int(
            getattr(cfg, "num_of_version_in_retention", 2))
        self._load_mirror = bool(getattr(cfg, "enable_nebula_load", True))
        self._load_path = getattr(cfg, "load_path", None)
        self._tag = None
        self._roots = set()          # save_dirs staged into this round
        self._fresh = set()          # (root, tag) staging dirs wiped this round
        self._last_persist = 0.0

    @property
    def supports_sharded(self):
        # transparent wrapper: sharded save/load capability is the inner
        # engine's (ShardedCheckpointEngine sets it)
        return getattr(self._inner, "supports_sharded", False)

    @property
    def supports_lazy(self):
        return getattr(self._inner, "supports_lazy", False)

    @property
    def aux_engine(self):
        """Consolidated-format engine whose saves STAGE through this
        tier: the engine's aux files (counters, host optimizer) must ride
        the same atomic publish — written directly into the final tag dir
        they would be destroyed when commit replaces it."""
        outer = self

        class _Aux(CheckpointEngine):
            def __init__(self):
                self._arr = ArrayCheckpointEngine()

            def save(self, state_dict, path):
                outer._stage(state_dict, path, self._arr)

            def load(self, path, map_location=None):
                return outer._load_with_fallback(path, self._arr,
                                                 map_location)

        return _Aux()

    @staticmethod
    def _split(path):
        """'<save_dir>/<tag>/<name>' -> (save_dir, tag, name)."""
        tag_dir, name = os.path.split(path)
        save_dir, tag = os.path.split(tag_dir)
        return save_dir or ".", tag, name

    def create(self, tag):
        super().create(tag)
        self._tag = str(tag)
        self._roots = set()
        self._fresh = set()

    def _staged_target(self, path):
        """Resolve ``path`` into the tag's staging dir, wiping crash
        leftovers before the round's first write: a CRASHED earlier run
        may have left partial staging here, and a publish must only ever
        contain this round's files (cross-process rollback — an
        in-memory flag can't see a previous process's leftovers). Every
        staged write — payload or sidecar — must come through here."""
        import shutil

        save_dir, tag, name = self._split(path)
        staged_dir = os.path.join(save_dir, ".staging", tag)
        if (save_dir, tag) not in self._fresh:
            shutil.rmtree(staged_dir, ignore_errors=True)
            self._fresh.add((save_dir, tag))
        self._roots.add(save_dir)
        return os.path.join(staged_dir, name)

    def _stage(self, state_dict, path, inner):
        inner.save(state_dict, self._staged_target(path))

    def save(self, state_dict, path):
        self._stage(state_dict, path, self._inner)

    def save_text(self, path, text):
        """Sidecar metadata (topology manifest) rides the SAME staged
        atomic publish as the payload — written into the final tag dir
        it would be destroyed when commit replaces that dir."""
        CheckpointEngine.save_text(self, self._staged_target(path), text)

    def save_bytes(self, path, blob):
        """Binary sidecars (the AOT program bundle) stage identically."""
        CheckpointEngine.save_bytes(self, self._staged_target(path), blob)

    def _load_with_fallback(self, path, inner, map_location=None,
                            loader=None):
        load = loader or (lambda p: inner.load(p, map_location=map_location))
        try:
            return load(path)
        except (OSError, FileNotFoundError):
            if not self._load_mirror:
                raise
            save_dir, tag, name = self._split(path)
            last_err = None
            # a crash inside a re-publish can strand the previous version
            # in <tag>.replaced — it is a complete checkpoint, recover it
            fallbacks = [os.path.join(save_dir, tag + ".replaced", name)]
            fallbacks += [os.path.join(base, tag, name) for base in
                          (self._load_path, self._persist_path) if base]
            for cand in fallbacks:
                try:
                    out = load(cand)
                    logger.warning(f"[ckpt] fast tier missing {path}; "
                                   f"restored from {cand}")
                    return out
                except (OSError, FileNotFoundError) as e:
                    last_err = e
            raise last_err or FileNotFoundError(path)

    def load(self, path, map_location=None):
        return self._load_with_fallback(path, self._inner, map_location)

    def load_lazy(self, path):
        """Slice-addressable load (reshard-at-load) with the same
        mirror fallback as :meth:`load`."""
        return self._load_with_fallback(path, self._inner,
                                        loader=self._inner.load_lazy)

    def commit(self, tag):
        import shutil
        import time

        from deepspeed_tpu import comm as dist

        tag = str(tag)
        self._inner.commit(tag)  # drain this process's async writes first
        dist.barrier()           # every process's staging is complete
        if dist.get_rank() == 0:
            for root in self._roots:
                staging_root = os.path.join(root, ".staging")
                staged = os.path.join(staging_root, tag)
                final = os.path.join(root, tag)
                trash = final + ".replaced"
                if not os.path.isdir(staged):
                    continue
                # durability before visibility: file contents first, then
                # the directory entries the publish renames touch
                for base, _, files in os.walk(staged):
                    for fn in files:
                        with open(os.path.join(base, fn), "rb") as f:
                            os.fsync(f.fileno())
                self._fsync_dir(staged)
                if not os.path.isdir(final) and os.path.isdir(trash):
                    # a previous commit crashed between its two renames:
                    # restore the stranded-but-complete old version before
                    # replacing it (load() also knows to read .replaced)
                    os.replace(trash, final)
                if os.path.isdir(final):
                    shutil.rmtree(trash, ignore_errors=True)
                    os.replace(final, trash)
                    os.replace(staged, final)  # atomic publish
                    shutil.rmtree(trash, ignore_errors=True)
                else:
                    os.replace(staged, final)  # atomic publish
                self._fsync_dir(root)  # the renames themselves
                # sweep staging left by abandoned tags (engine-owned dir)
                for stale in os.listdir(staging_root):
                    shutil.rmtree(os.path.join(staging_root, stale),
                                  ignore_errors=True)
                self._mirror(root, tag, time.time())
        dist.barrier()           # peers wait for the publish
        self._roots = set()
        self._fresh = set()
        return True

    _fsync_dir = staticmethod(fsync_dir)

    # -- durable mirror -------------------------------------------------
    def _manifest(self):
        return os.path.join(self._persist_path, ".tiered_manifest.json")

    def _mirror(self, root, tag, now):
        import shutil

        if not self._persist_path:
            return
        if now - self._last_persist < self._persist_interval:
            return  # fast-tier only this round (reference scratch cadence)
        os.makedirs(self._persist_path, exist_ok=True)
        dst = os.path.join(self._persist_path, tag)
        tmp = dst + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.copytree(os.path.join(root, tag), tmp)
        if os.path.isdir(dst):
            shutil.rmtree(dst, ignore_errors=True)
        os.replace(tmp, dst)
        self._last_persist = now
        published = []
        if os.path.exists(self._manifest()):
            try:
                with open(self._manifest()) as f:
                    published = json.load(f)
            except (ValueError, OSError):
                # a crash mid-dump must not brick every later commit;
                # worst case some old mirror versions escape pruning
                logger.warning("[ckpt] mirror manifest unreadable; "
                               "restarting retention tracking")
        published = [t for t in published if t != tag] + [tag]
        # retention: prune only versions this engine published
        while len(published) > max(1, self._retention):
            victim = published.pop(0)
            shutil.rmtree(os.path.join(self._persist_path, victim),
                          ignore_errors=True)
        mtmp = self._manifest() + ".tmp"
        with open(mtmp, "w") as f:
            json.dump(published, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(mtmp, self._manifest())
        log_dist(f"[ckpt] mirrored {tag} to {self._persist_path} "
                 f"(retention {self._retention})", ranks=[0])
