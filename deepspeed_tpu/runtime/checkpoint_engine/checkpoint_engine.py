"""Checkpoint engine abstraction (reference
``runtime/checkpoint_engine/checkpoint_engine.py``: pluggable
save/load/commit used by the engine; Torch and Nebula impls).

Implementations here:
- :class:`ArrayCheckpointEngine` — synchronous npz+json format (the
  ``TorchCheckpointEngine`` equivalent).
- :class:`OrbaxCheckpointEngine` — async sharded checkpointing via orbax
  (the Nebula-equivalent async tier), used when ``checkpoint.async_save``.
"""

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine:
    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        log_dist(f"[ckpt] Saving checkpoint: {tag}", ranks=[0])

    def save(self, state_dict: Dict[str, Any], path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        raise NotImplementedError

    def commit(self, tag):
        return True

    def makedirs(self, path, exist_ok=False):
        os.makedirs(path, exist_ok=exist_ok)


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    """Flatten nested dict/tuple/list/namedtuple structure to {path: leaf}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif hasattr(tree, "_fields"):  # namedtuple
        for k in tree._fields:
            out.update(_flatten(getattr(tree, k), f"{prefix}{k}/"))
    elif isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is None:
        out[prefix.rstrip("/") + "#none"] = None
    else:
        out[prefix.rstrip("/")] = tree
    return out


class ArrayCheckpointEngine(CheckpointEngine):
    """npz (arrays) + json (structure/scalars) on the filesystem.

    ``save`` expects a dict whose leaves are arrays / python scalars /
    strings; arbitrary nesting (incl. namedtuples) is flattened with
    path-keys, so ``load`` returns a flat ``{path: value}`` mapping plus the
    original metadata — the engine re-assembles pytrees from its own treedef.
    """

    def save(self, state_dict: Dict[str, Any], path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        flat = _flatten(state_dict)
        arrays, meta = {}, {}
        for k, v in flat.items():
            if k.endswith("#none"):
                meta[k] = None
            elif hasattr(v, "shape"):
                arrays[k] = np.asarray(v)
            else:
                meta[k] = v
        np.savez(path + ".npz", **arrays)
        with open(path + ".json", "w") as f:
            json.dump(meta, f, default=str)

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        flat = {}
        with np.load(path + ".npz", allow_pickle=False) as z:
            for k in z.files:
                flat[k] = z[k]
        if os.path.exists(path + ".json"):
            with open(path + ".json") as f:
                meta = json.load(f)
            for k, v in meta.items():
                if k.endswith("#none"):
                    flat[k[:-len("#none")]] = None
                else:
                    flat[k] = v
        return flat


class OrbaxCheckpointEngine(CheckpointEngine):
    """Async sharded checkpointing (orbax) — the reference's Nebula slot
    (``nebula_checkpoint_engine.py:15``): commit() waits for the async save."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._manager = None
        self._pending = []

    def save(self, state_dict, path):
        ckptr = self._ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path) + ".orbax", state_dict, force=True)
        self._pending.append(ckptr)

    def load(self, path, map_location=None):
        ckptr = self._ocp.StandardCheckpointer()
        return ckptr.restore(os.path.abspath(path) + ".orbax")

    def commit(self, tag):
        for c in self._pending:
            c.wait_until_finished()
        self._pending.clear()
        return True


class ShardedCheckpointEngine(OrbaxCheckpointEngine):
    """Sharded checkpointing WITHOUT consolidation.

    The engine hands this checkpointer its live sharded ``jax.Array`` trees;
    orbax/tensorstore writes only each host's addressable shards, in
    parallel across hosts, and restore re-shards onto whatever mesh the
    loading engine runs — the universal-checkpoint capability (reference
    ``checkpoint/universal_checkpoint.py:13``, ZeRO elastic reshaping
    ``stage_1_and_2.py:2131``) as a storage-layer property instead of
    offline reshape scripts. ``save`` must be called by ALL processes
    (collective); ``commit`` barriers on write completion (the reference's
    tag-commit semantics, ``engine.py:3043``).
    """

    supports_sharded = True

    def load_sharded(self, path, abstract_tree):
        """Restore onto the shardings carried by ``abstract_tree`` leaves
        (jax.ShapeDtypeStruct with ``.sharding`` set): each host reads only
        the byte ranges its shards need."""
        ckptr = self._ocp.StandardCheckpointer()
        return ckptr.restore(os.path.abspath(path) + ".orbax",
                             target=abstract_tree)
