from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec,
                                               partition_balanced)
from deepspeed_tpu.runtime.pipe.schedule import (DataParallelSchedule,
                                                 InferenceSchedule,
                                                 TrainSchedule)

__all__ = ["LayerSpec", "TiedLayerSpec", "PipelineModule", "partition_balanced",
           "TrainSchedule", "InferenceSchedule", "DataParallelSchedule"]
