"""Pipeline-parallel training engine — the compiled 1F1B/GPipe schedule.

Capability parity with the reference ``PipelineEngine``
(``deepspeed/runtime/pipe/engine.py:36``): ``train_batch(data_iter)`` runs
``gas`` micro-batches through the stage pipeline and applies the optimizer.
The reference interprets a ``TrainSchedule`` instruction list with imperative
P2P sends (``pipe/p2p.py``) and per-buffer autograd; on TPU the *entire*
schedule is one XLA program:

- stages live on the ``pipe`` mesh axis; the model's repeated blocks are
  sharded over it (``PipelineModule``);
- a ``shard_map`` manual over ``pipe`` (auto/GSPMD over data/model/seq axes)
  runs ``M + P - 1`` "clock ticks"; each tick every stage applies its blocks
  and passes its activation to the next stage via ``lax.ppermute`` — the
  SendActivation/RecvActivation instructions;
- stage 0 injects micro-batch ``t`` (LoadMicroBatch) and the last stage
  computes the loss for micro-batch ``t - (P-1)`` under ``lax.cond`` so other
  stages skip the embedding/head FLOPs;
- ``jax.grad`` through the scan-of-ticks *is* the backward schedule: the
  transpose of ``ppermute`` sends grads backwards (SendGrad/RecvGrad), the
  transpose of the replicated-in tied/pre/post params is the tied-grad
  all-reduce over ``pipe`` (ReduceTiedGrads), and GSPMD's data-axis psum is
  ReduceGrads. Each tick is ``jax.checkpoint``-ed, so backward recomputes one
  tick's activations at a time (activation-checkpoint-per-micro-batch — the
  1F1B memory profile rather than GPipe's all-activations-live).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import AXIS_PIPE
from deepspeed_tpu.runtime.engine import DeepSpeedEngine, TrainState
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.schedule import (InterleavedSchedule,
                                                 TrainSchedule,
                                                 ZeroBubbleSchedule)
from deepspeed_tpu.runtime.zero.partition import replicated
from deepspeed_tpu.utils.compat import (partial_auto_shard_map_safe,
                                        shard_map)
from deepspeed_tpu.utils.logging import log_dist


def _cond_skip(pred, fn, false_val, operands):
    """``lax.cond(pred, fn(operands), false_val)`` with an opaque VJP.

    With rng primitives inside one branch only, ``lax.scan``'s partial
    evaluation of the cond asserts on asymmetric branch residuals
    (``jax/_src/lax/control_flow/conditionals.py:619``). Hiding the cond
    behind a ``custom_vjp`` keeps it atomic to the scan: the backward pass
    re-linearizes the cond from its saved inputs — the same
    recompute-per-tick memory profile the tick remat already imposes —
    while the forward still executes only the taken branch (the
    FLOP-skipping the reference's per-stage instruction dispatch gets for
    free). ``fn`` must take ALL traced values through ``operands`` (a
    closure over tracers would leak through the custom_vjp boundary).
    """

    @jax.custom_vjp
    def run(pred, false_val, operands):
        return jax.lax.cond(pred, lambda: fn(operands), lambda: false_val)

    def fwd(pred, false_val, operands):
        return run(pred, false_val, operands), (pred, false_val, operands)

    def bwd(res, g):
        pred_, false_val_, operands_ = res
        _, vjp_fn = jax.vjp(
            lambda fv, ops: jax.lax.cond(
                pred_, lambda: fn(ops), lambda: fv),
            false_val_, operands_)
        d_fv, d_ops = vjp_fn(g)
        return (None, d_fv, d_ops)

    run.defvjp(fwd, bwd)
    return run(pred, false_val, operands)


def pipeline_loss_fn(module: PipelineModule, mesh, n_micro: int,
                     virtual_stages: int = 1):
    """Build ``loss(params, (inputs, labels), rng) -> mean loss`` running the
    pipelined schedule over ``n_micro`` micro-batches.

    ``inputs``/``labels`` are [M, mb, ...]; blocks params are [L, ...] sharded
    over ``pipe`` (L/P per stage).

    ``virtual_stages > 1`` compiles the interleaved schedule: each physical
    stage owns ``v`` round-robin layer chunks (virtual stage ``u = j*P + s``
    holds layers ``[u*Lc, (u+1)*Lc)``); a micro-batch rides the same
    ppermute ring ``v`` times, advancing one *virtual* stage per tick, so
    warmup/cooldown ramps fill ``v``x faster — the bubble shrinks toward
    ``(P-1)/(Mv+P-1)`` for ``v``x the per-stage activation traffic. Micro-
    batch ``m`` injects at tick ``(m % P) + (m // P)*v*P``; at tick ``t``
    stage ``s`` computes chunk ``j = ((t-s) // P) % v`` of micro-batch
    ``((t-s)//P//v)*P + (t-s)%P``. ``virtual_stages == 1`` traces the
    exact 1F1B program (HLO byte-identity pinned in tests)."""
    n_stages = mesh.shape[AXIS_PIPE]
    v = int(virtual_stages)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    use_rngs = module.use_rngs
    # micro-batches live SHARDED over the pipe axis (stage s holds the
    # strided chunk {s, s+P, s+2P, ...} — M/P per stage, not M replicated
    # copies); each tick the owner stage publishes one micro-batch to the
    # ring via a where+psum select. Reference analog: LoadMicroBatch only
    # ever materializes data on stage 0 (pipe/engine.py:785).
    n_chunk = -(-n_micro // n_stages)
    n_pad = n_chunk * n_stages

    def body(params, inputs, labels, rng):
        stage = jax.lax.axis_index(AXIS_PIPE)
        extras = {"pre": params["pre"], "post": params["post"],
                  "tied": params["tied"]}
        blocks = params["blocks"]  # local view: [L/P, ...]
        # local strided chunks: [1, Mc, mb, ...] -> [Mc, mb, ...]
        inputs = jax.tree_util.tree_map(lambda a: a[0], inputs)
        labels = jax.tree_util.tree_map(lambda a: a[0], labels)

        def fetch(chunk, idx, owner):
            """Micro-batch ``idx`` (held by ``owner``'s chunk) delivered to
            every stage: owner publishes, psum routes. Transient — nothing
            [M]-sized is ever resident per stage."""
            if n_stages == 1:  # single stage owns everything; psum over a
                # size-1 manual axis trips the SPMD partitioner
                return jax.tree_util.tree_map(
                    lambda a: a[jnp.clip(idx, 0, n_chunk - 1)], chunk)

            def sel(a):
                row = a[jnp.clip(idx, 0, n_chunk - 1)]
                keep = (stage == owner).astype(a.dtype)
                shaped = keep.reshape((1,) * row.ndim)
                return jax.lax.psum(row * shaped, AXIS_PIPE)

            return jax.tree_util.tree_map(sel, chunk)

        def run_blocks(x, t, chunk=None):
            bp_stack = blocks
            if v > 1:
                # local blocks are [v, Lc, ...]; run this tick's chunk
                bp_stack = jax.tree_util.tree_map(
                    lambda b: jax.lax.dynamic_index_in_dim(
                        b, chunk, axis=0, keepdims=False), blocks)

            def blk(x, bp):
                return module.block_apply(bp, x,
                                          rngs=rngs_of(t, stage, rng)), None

            x, _ = jax.lax.scan(blk, x, bp_stack)
            return x

        mb0 = jax.tree_util.tree_map(lambda a: a[0], inputs)  # local shape
        act_shape = jax.eval_shape(
            lambda p, b: module.pre_apply(p, b), extras, mb0)
        zero_act = jnp.zeros(act_shape.shape, act_shape.dtype)

        def rngs_of(t, st, r):
            # every traced dependency (t, stage, rng key) arrives as an
            # argument: pre_fn/loss_of run inside _cond_skip's custom_vjp,
            # where a closure over an outer tracer would leak
            if not use_rngs:
                return None
            return {"dropout": jax.random.fold_in(
                jax.random.fold_in(r, t), st)}

        def pre_fn(ops):
            extras_, mb_, t_, st_, r_ = ops
            return module.pre_apply(extras_, mb_, rngs=rngs_of(t_, st_, r_))

        def loss_of(ops):
            extras_, y_, lab_, t_, st_, r_ = ops
            loss = module.loss_fn(
                module.post_apply(extras_, y_, rngs=rngs_of(t_, st_, r_)),
                lab_).astype(jnp.float32)
            # the per-tick loss is carried as [1], not a scalar: jax < 0.5's
            # shard_map transpose mis-names scalar float32 scan carries
            # ({0: all_axes} on a rank-0 aval) and grad fails to trace;
            # rank-1 is spec-legal on every path and numerically identical
            return loss.reshape((1,))

        def stage_select(pred, fn, false_val, operands):
            # lax.cond skips the untaken branch's FLOPs at runtime —
            # embedding/head work runs only on its own stage, bubble ticks
            # pay nothing. With dropout rngs a plain cond trips scan's
            # branch-residual assertion; _cond_skip wraps it atomically
            # (round-2's both-branch jnp.where fallback is gone).
            if not use_rngs:
                return jax.lax.cond(pred, lambda: fn(operands),
                                    lambda: false_val)
            return _cond_skip(pred, fn, false_val, operands)

        @jax.checkpoint
        def tick(carry, t):
            state, loss_sum, count = carry
            if v == 1:
                # micro-batch t lives in chunk slot t//P on stage t%P
                mb = fetch(inputs, t // n_stages, jnp.mod(t, n_stages))
                # LoadMicroBatch on stage 0; other stages use received act
                x = stage_select(stage == 0, pre_fn, state,
                                 (extras, mb, t, stage, rng))
                y = run_blocks(x, t)
                # last stage: loss of micro-batch t-(P-1) (if one arrived)
                out_idx = t - (n_stages - 1)
                lab = fetch(labels, out_idx // n_stages,
                            jnp.mod(out_idx, n_stages))
                take = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            else:
                # interleaved: stage s at tick t runs chunk
                # j = ((t-s)//P) % v of micro-batch g*P + r where
                # r = (t-s)%P, g = (t-s)//P//v (docstring algebra)
                a = t - stage
                chunk = jnp.mod(a // n_stages, v)
                # chunk-0 injection on stage 0: mb ((t//P)//v)*P + t%P,
                # held by chunk slot (t//P)//v of its owner stage t%P
                inject = jnp.logical_and(
                    stage == 0, jnp.mod(t // n_stages, v) == 0)
                mb = fetch(inputs, (t // n_stages) // v,
                           jnp.mod(t, n_stages))
                x = stage_select(inject, pre_fn, state,
                                 (extras, mb, t, stage, rng))
                y = run_blocks(x, t, chunk=chunk)
                # loss leg: last stage, deepest chunk v-1
                a_out = t - (n_stages - 1)
                r_out = jnp.mod(a_out, n_stages)
                g_out = (a_out // n_stages) // v
                m_out = g_out * n_stages + r_out
                lab = fetch(labels, g_out, r_out)
                take = ((stage == n_stages - 1)
                        & (jnp.mod(a_out // n_stages, v) == v - 1)
                        & (a_out >= 0) & (m_out < n_micro))
            loss_t = stage_select(take, loss_of, jnp.zeros((1,), jnp.float32),
                                  (extras, y, lab, t, stage, rng))
            loss_sum = loss_sum + loss_t
            count = count + take.astype(jnp.int32)
            # SendActivation/RecvActivation: rotate stage outputs forward
            state = jax.lax.ppermute(y, AXIS_PIPE, perm)
            return (state, loss_sum, count), None

        if v == 1:
            total_ticks = n_micro + n_stages - 1
        else:
            # last micro-batch injects at tau = (M-1)%P + ((M-1)//P)*v*P
            # and needs v*P more ticks to clear all virtual stages
            tau_last = ((n_micro - 1) % n_stages
                        + ((n_micro - 1) // n_stages) * v * n_stages)
            total_ticks = tau_last + v * n_stages
        (_, loss_sum, count), _ = jax.lax.scan(
            tick, (zero_act, jnp.zeros((1,), jnp.float32),
                   jnp.zeros((), jnp.int32)),
            jnp.arange(total_ticks))
        # broadcast the last stage's mean loss to all stages
        loss_sum = jax.lax.psum(loss_sum, AXIS_PIPE)[0]
        count = jax.lax.psum(count, AXIS_PIPE)
        return loss_sum / count.astype(jnp.float32)

    # v > 1: blocks arrive pre-reshaped [v, L/v, ...] (loss_fn below), so
    # the pipe axis shards dim 1 — stage s owns chunk rows [j, s*Lc:(s+1)*Lc)
    blocks_spec = P(AXIS_PIPE) if v == 1 else P(None, AXIS_PIPE)
    spec_params = {"pre": P(), "blocks": blocks_spec, "post": P(), "tied": P()}
    smapped = shard_map(
        body, mesh=mesh,
        in_specs=(spec_params, P(AXIS_PIPE), P(AXIS_PIPE), P()),
        out_specs=P(),
        axis_names={AXIS_PIPE},
        check_vma=False)

    def stride(a):
        """[M, mb, ...] -> [P, Mc, mb, ...] with slot [s, k] = a[s + kP]
        (zero-padded to Mc*P): sharding the leading axis over pipe puts
        chunk s on stage s."""
        if n_pad > n_micro:
            a = jnp.concatenate(
                [a, jnp.zeros((n_pad - n_micro,) + a.shape[1:], a.dtype)], 0)
        return a.reshape((n_chunk, n_stages) + a.shape[1:]).swapaxes(0, 1)

    def loss_fn(params, batch, rngs=None):
        inputs, labels = batch
        if v > 1:
            # [L, ...] -> [v, L/v, ...]: row [j, s*Lc + i] is layer
            # (j*P + s)*Lc + i, i.e. virtual stage j*P + s owns a
            # round-robin chunk (free reshape; the pipe resharding of
            # dim 1 is the interleaving's extra param traffic)
            params = dict(params)
            params["blocks"] = jax.tree_util.tree_map(
                lambda b: b.reshape((v, b.shape[0] // v) + b.shape[1:]),
                params["blocks"])
        inputs = jax.tree_util.tree_map(stride, inputs)
        labels = jax.tree_util.tree_map(stride, labels)
        rng = rngs["dropout"] if isinstance(rngs, dict) else (
            rngs if rngs is not None else jax.random.PRNGKey(0))
        return smapped(params, inputs, labels, rng)

    return loss_fn


class PipelineEngine(DeepSpeedEngine):
    """Training engine for :class:`PipelineModule` models.

    ``forward``/``train_batch`` consume a *full* batch (``gas`` micro-batches
    at once) because the pipelined schedule over all micro-batches is a
    single compiled program; ``is_gradient_accumulation_boundary`` is
    therefore always True (reference parity: ``PipelineEngine.train_batch``
    also hides micro-batching from the user).
    """

    def __init__(self, *args, **kwargs):
        model = kwargs.get("model")
        if model is None and len(args) >= 2:
            model = args[1]
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a PipelineModule"
        self._pipe_module = model
        self._pipe_ready = False
        # super().__init__ may already build state (model_parameters given),
        # which routes through _compile_steps → _finalize_pipe_setup
        super().__init__(*args, **kwargs)
        self._finalize_pipe_setup()

    def _finalize_pipe_setup(self):
        """Validate topology/config once both are parsed. Called from both
        ``__init__`` and ``_compile_steps`` (whichever runs first — state may
        be built inside ``super().__init__`` when params are passed in)."""
        if self._pipe_ready:
            return
        if self.zero_optimization_stage() > 2:
            raise ValueError(
                "ZeRO-3 is incompatible with pipeline parallelism "
                "(reference parity: engine.py asserts the same); use stage<=2")
        n_stages = self.topology.get_pipe_parallel_world_size()
        auto_extent = [f"{ax}={n}" for ax, n in self.mesh.shape.items()
                       if ax != AXIS_PIPE and n > 1]
        if auto_extent and not partial_auto_shard_map_safe():
            # jax < 0.5 cannot compile the pipe-manual shard_map next to
            # live auto axes — the backward pass SIGABRTs inside XLA
            # (IsManualSubgroup CHECK) instead of raising. Refuse with a
            # Python error before any compile is attempted.
            raise RuntimeError(
                "pipeline parallelism composed with other mesh axes "
                f"({', '.join(auto_extent)}) requires jax >= 0.5; this "
                "runtime hard-crashes compiling the partially-manual "
                "program. Use a pipe-only mesh or upgrade jax.")
        pipe_cfg = self._config.pipeline_config
        self.pipe_schedule = pipe_cfg.schedule
        self.virtual_stages = (pipe_cfg.virtual_stages
                               if pipe_cfg.schedule == "interleaved" else 1)
        self._pipe_module.validate_stages(
            n_stages, virtual_stages=self.virtual_stages)
        self.num_stages = n_stages
        self.micro_batches = self.gradient_accumulation_steps()
        self._pipe_ready = True
        log_dist(
            f"PipelineEngine: stages={n_stages} micro_batches="
            f"{self.micro_batches} schedule={self.pipe_schedule} "
            f"virtual_stages={self.virtual_stages} blocks/stage="
            f"{self._pipe_module.n_blocks // n_stages}", ranks=[0])

    # the PipelineModule is not a plain loss fn — the pipelined loss is
    # built in _compile_steps
    def _resolve_loss_fn(self, model):
        def unavailable(*a, **k):
            raise RuntimeError("pipeline loss is compiled in _compile_steps")

        return unavailable

    def _tp_base_specs(self, params_abstract):
        """Blocks carry the leading layer axis sharded over ``pipe``; pre/
        post/tied replicated (tied-layer replication, ``module.py:420``)."""
        def spec_blocks(leaf):
            return P(AXIS_PIPE, *([None] * (leaf.ndim - 1)))

        return {
            "pre": jax.tree_util.tree_map(lambda _: None, params_abstract["pre"]),
            "blocks": jax.tree_util.tree_map(
                spec_blocks, params_abstract["blocks"],
                is_leaf=lambda x: hasattr(x, "shape")),
            "post": jax.tree_util.tree_map(lambda _: None, params_abstract["post"]),
            "tied": jax.tree_util.tree_map(lambda _: None, params_abstract["tied"]),
        }

    def _init_params(self, batch):
        inputs, _ = self._split_batch_labels(batch)
        mb = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[: self._micro_batch_rows()], inputs)
        seed = self._config._param_dict.get("seed", 42)
        params = self._pipe_module.init_params(jax.random.PRNGKey(seed), mb)
        return params

    def _micro_batch_rows(self) -> int:
        return (self.train_micro_batch_size_per_gpu()
                * self.topology.get_data_parallel_world_size())

    @staticmethod
    def _split_batch_labels(batch):
        if isinstance(batch, dict):
            inputs = batch["input_ids"] if "input_ids" in batch else batch["inputs"]
            labels = batch.get("labels", inputs)
            return inputs, labels
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            return batch[0], batch[1]
        return batch, batch

    def _compile_steps(self):
        self._finalize_pipe_setup()
        n_micro = self.micro_batches
        mesh = self.mesh
        pipe_loss = pipeline_loss_fn(self._pipe_module, mesh, n_micro,
                                     virtual_stages=self.virtual_stages)
        fp16 = self.fp16_enabled_
        grad_shardings = self._state_shardings.grad_acc
        mb_rows = self._micro_batch_rows()

        def to_micro(a):
            return a.reshape((n_micro, mb_rows) + a.shape[1:])

        self._pipe_loss = pipe_loss
        self._to_micro = to_micro

        def micro_step(state: TrainState, batch):
            rng, sub = jax.random.split(state.rng)
            inputs, labels = self._split_batch_labels(batch)
            inputs = jax.tree_util.tree_map(to_micro, inputs)
            labels = jax.tree_util.tree_map(to_micro, labels)

            def scaled_loss(p):
                loss = pipe_loss(p, (inputs, labels),
                                 rngs={"dropout": sub}
                                 if self._pipe_module.use_rngs else None)
                return loss * (state.loss_scale.loss_scale if fp16 else 1.0)

            loss_scaled, grads = jax.value_and_grad(scaled_loss)(state.params)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            grad_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), state.grad_acc, grads)
            loss = loss_scaled / (state.loss_scale.loss_scale if fp16 else 1.0)
            return state._replace(grad_acc=grad_acc, rng=rng), loss

        shardings = self._state_shardings
        self._jit_micro = self.telemetry.watch_jit(
            jax.jit(
                micro_step,
                in_shardings=(shardings, None),
                out_shardings=(shardings, replicated(mesh)),
                donate_argnums=(0,)),
            "pipe.micro_step")
        # reuse the base apply_step (optimizer/clip/loss-scale machinery)
        super()._compile_steps_apply_only()

    def is_gradient_accumulation_boundary(self) -> bool:
        return True

    def train_batch(self, data_iter=None, batch=None):
        """One full optimizer step: ``gas`` micro-batches through the
        pipeline (reference ``pipe/engine.py:294``)."""
        if batch is None:
            with self.telemetry.step_trace.phase("data"):
                parts = [next(data_iter) for _ in range(self.micro_batches)]
                batch = jax.tree_util.tree_map(
                    # host-side batch assembly from the data iterator (input
                    # marshaling, not a device readback)
                    lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *parts)  # graft-lint: disable=GL04
        loss = self.forward(batch)
        self.backward(loss)
        self.step()
        loss = float(loss)
        # this float() already paid the device sync — hand the value to
        # the step sentinel so its lagged fetch for this boundary is
        # superseded (no second sync, and the pipelined schedule's loss
        # is judged the step it happened, not sync_lag boundaries later)
        self.resilience.observe_synced_loss(self.global_steps, loss)
        return loss

    def eval_batch(self, batch):
        batch = self._shard_batch(batch)
        self._ensure_state(batch)
        if not hasattr(self, "_jit_eval"):
            pipe_loss, to_micro = self._pipe_loss, self._to_micro

            def eval_loss(params, batch):
                inputs, labels = self._split_batch_labels(batch)
                return pipe_loss(params,
                                 (jax.tree_util.tree_map(to_micro, inputs),
                                  jax.tree_util.tree_map(to_micro, labels)))

            self._jit_eval = self.telemetry.watch_jit(
                jax.jit(
                    eval_loss,
                    in_shardings=(self._state_shardings.params, None),
                    out_shardings=replicated(self.mesh)),
                "pipe.eval_step")
        return self._jit_eval(self.state.params, batch)

    def train_schedule(self, stage_id: int = 0) -> TrainSchedule:
        """The instruction schedule this engine's compiled program realizes
        (for inspection/validation — reference ``TrainSchedule``), selected
        by ``pipeline.schedule``. ``zero_bubble`` models the B/W split XLA's
        scan transpose already performs (losses stay bit-identical to 1f1b);
        ``interleaved`` mirrors the virtual-stage program compiled above."""
        if self.pipe_schedule == "interleaved":
            return InterleavedSchedule(micro_batches=self.micro_batches,
                                       stages=self.num_stages,
                                       stage_id=stage_id,
                                       virtual_stages=self.virtual_stages)
        if self.pipe_schedule == "zero_bubble":
            return ZeroBubbleSchedule(micro_batches=self.micro_batches,
                                      stages=self.num_stages,
                                      stage_id=stage_id)
        return TrainSchedule(micro_batches=self.micro_batches,
                             stages=self.num_stages, stage_id=stage_id)
