"""Pipeline-parallel model container.

Capability parity with the reference ``deepspeed/runtime/pipe/module.py``
(``LayerSpec:85``-style lazy layer construction, ``PipelineModule`` layer
partitioning by uniform/parameters/type-regex at ``:364``, tied-layer
replication at ``:420-442``), re-designed for SPMD execution:

- the repeated middle run of identical layers ("blocks") carries a leading
  layer axis and is **sharded over the ``pipe`` mesh axis** — stage ``s``
  physically holds layers ``[s*L/P, (s+1)*L/P)``;
- prelude layers (embeddings) and postlude layers (final norm / head) are
  replicated over ``pipe`` but only *executed* on the first / last stage
  (a ``lax.cond`` on the stage index — the other stages skip the FLOPs);
- tied layers (``TiedLayerSpec``) share one parameter entry; replication +
  gradient all-reduce over ``pipe`` is exactly the reference's tied-weight
  semantics, and falls out of the ``shard_map`` transpose for free.

The compiled schedule itself lives in ``runtime/pipe/engine.py``.
"""

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Lazily-built layer: class + ctor args (reference ``module.py:85``).

    ``typename`` may be a flax ``nn.Module`` subclass or any class whose
    instances are plain callables ``f(x)`` (parameter-free).
    """

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not isinstance(typename, type) and not callable(typename):
            raise RuntimeError("LayerSpec requires a class or callable")

    @property
    def type_name(self) -> str:
        return getattr(self.typename, "__name__", str(self.typename))

    def build(self):
        if isinstance(self.typename, type):
            return self.typename(*self.module_args, **self.module_kwargs)
        return self.typename  # already a callable/function

    def __repr__(self):
        return f"LayerSpec({self.type_name})"


class TiedLayerSpec(LayerSpec):
    """A layer whose parameters are shared with every other spec of the same
    ``key`` (reference ``module.py:104``). ``forward_fn(params, x)`` overrides
    the module's ``__call__`` for re-uses (e.g. embedding re-used as LM head).
    """

    def __init__(self, typename, *module_args, key: str,
                 forward_fn: Optional[Callable] = None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn

    def __repr__(self):
        return f"TiedLayerSpec({self.type_name}, key={self.key!r})"


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Contiguous partition of ``weights`` into ``num_parts`` minimizing the
    max part weight (reference ``deepspeed/runtime/utils.py`` /
    ``module.py:364`` "parameters" method). Returns ``num_parts + 1``
    boundaries. Binary search over the bottleneck + greedy feasibility check.
    """
    n = len(weights)
    num_parts = min(num_parts, max(n, 1))
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def parts_needed(cap: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            # furthest end with sum(weights[start:end]) <= cap
            end = int(np.searchsorted(prefix, prefix[start] + cap, side="right")) - 1
            if end <= start:
                if start >= n:
                    end = start
                else:
                    return None  # single item exceeds cap
            end = min(end, n)
            bounds.append(end)
            start = end
        return bounds if bounds[-1] >= n else None

    lo = max(weights) if weights else 0.0
    hi = float(prefix[-1]) or 1.0
    best = parts_needed(hi)
    for _ in range(50):
        mid = (lo + hi) / 2
        b = parts_needed(mid)
        if b is not None:
            best, hi = b, mid
        else:
            lo = mid
    assert best is not None
    best[-1] = n
    return best


def _is_flax_module(obj) -> bool:
    return hasattr(obj, "init") and hasattr(obj, "apply")


class _BuiltLayer:
    """A constructed layer with a uniform functional interface."""

    def __init__(self, spec_or_module, index: int):
        self.index = index
        self.tied_key: Optional[str] = None
        self.forward_fn: Optional[Callable] = None
        if isinstance(spec_or_module, TiedLayerSpec):
            self.tied_key = spec_or_module.key
            self.forward_fn = spec_or_module.forward_fn
            self.module = spec_or_module.build()
            self.type_name = spec_or_module.type_name
        elif isinstance(spec_or_module, LayerSpec):
            self.module = spec_or_module.build()
            self.type_name = spec_or_module.type_name
        else:
            self.module = spec_or_module
            self.type_name = type(spec_or_module).__name__
        self.has_params = _is_flax_module(self.module)
        self.accepts_deterministic = False
        if self.has_params:
            import inspect

            try:
                sig = inspect.signature(type(self.module).__call__)
                self.accepts_deterministic = "deterministic" in sig.parameters
            except (TypeError, ValueError):
                pass

    def init(self, rng, x):
        if not self.has_params:
            return {}
        return self.module.init(rng, x)["params"]

    def apply(self, params, x, rngs=None):
        if self.forward_fn is not None:
            return self.forward_fn(params, x)
        if not self.has_params:
            return self.module(x)
        kwargs = {}
        if self.accepts_deterministic:
            # train mode ⇔ rngs supplied (matches the non-pipeline engine,
            # whose loss_fn sets deterministic=rngs is None)
            kwargs["deterministic"] = rngs is None
        return self.module.apply({"params": params}, x, rngs=rngs, **kwargs)


class PipelineModule:
    """A model expressed as a layer sequence, partitioned over pipe stages.

    Engine contract (consumed by ``PipelineEngine``):
    - ``init_params(rng, example_batch)`` → ``{"pre": [...], "blocks": <stacked
      [L, ...]>, "post": [...], "tied": {key: params}}``
    - ``pre_apply(params, inputs, rngs)`` → first activation (stage 0 work)
    - ``block_apply(block_params_one_layer, x, rngs)`` → x
    - ``post_apply(params, x, rngs)`` → model output (last stage work)
    - ``loss_fn(outputs, labels)`` → scalar loss
    """

    def __init__(self,
                 layers: Sequence,
                 loss_fn: Optional[Callable] = None,
                 num_stages: Optional[int] = None,
                 topology=None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 use_rngs: bool = False):
        self.specs = list(layers)
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.use_rngs = use_rngs
        if topology is not None:
            self.num_stages = topology.get_pipe_parallel_world_size()
        else:
            self.num_stages = num_stages  # may be None → resolved by engine
        self._layers = [_BuiltLayer(s, i) for i, s in enumerate(self.specs)]
        self._split_layers()

    # ------------------------------------------------------------------
    def _split_layers(self):
        """Find the maximal homogeneous middle run — the pipelined blocks."""
        names = [l.type_name for l in self._layers]
        best = (0, 0)  # [start, end)
        i = 0
        while i < len(names):
            j = i
            while j < len(names) and names[j] == names[i] \
                    and self._layers[j].tied_key is None \
                    and self._layers[j].has_params:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = max(j, i + 1)
        self._block_start, self._block_end = best
        if best[1] - best[0] == 0:
            raise ValueError(
                "PipelineModule requires a run of >=1 identical parameterized "
                f"layers to pipeline; got layer types {names}")
        self.pre_layers = self._layers[:self._block_start]
        self.block_layers = self._layers[self._block_start:self._block_end]
        self.post_layers = self._layers[self._block_end:]
        self.n_blocks = len(self.block_layers)
        self._block_module = self.block_layers[0].module

    def validate_stages(self, num_stages: int, virtual_stages: int = 1):
        self.num_stages = num_stages
        if self.n_blocks % (num_stages * virtual_stages) != 0:
            detail = (f"{num_stages} pipeline stages"
                      if virtual_stages == 1 else
                      f"{num_stages} stages x {virtual_stages} virtual "
                      f"stages (interleaved chunks)")
            raise ValueError(
                f"{self.n_blocks} pipelined layers not divisible by "
                f"{detail}")

    # ------------------------------------------------------------------
    def layer_weights(self, params=None) -> List[float]:
        """Per-layer balance weights for ``partition_method``."""
        method = self.partition_method.lower()
        if method == "uniform":
            return [1.0] * len(self._layers)
        if method.startswith("type:"):
            regex = method[len("type:"):]
            return [1.0 if re.search(regex, l.type_name, re.IGNORECASE) else 0.0
                    for l in self._layers]
        if method == "parameters":
            if params is None:
                return [1.0 if l.has_params else 0.0 for l in self._layers]
            sizes = []
            for l in self._layers:
                p = params.get(f"layer_{l.index}", {})
                sizes.append(float(sum(np.prod(x.shape) for x in
                                       jax.tree_util.tree_leaves(p))))
            return sizes
        raise NotImplementedError(f"partition_method={self.partition_method}")

    def partition_layers(self, num_stages: Optional[int] = None) -> List[int]:
        """Stage boundaries over the full layer list (advisory: the SPMD
        executor always splits the homogeneous block run uniformly, which for
        transformer stacks coincides with the balanced partition)."""
        num_stages = num_stages or self.num_stages or 1
        bounds = partition_balanced(self.layer_weights(), num_stages)
        logger.info(f"PipelineModule partition: {bounds}")
        return bounds

    # ------------------------------------------------------------------
    def init_params(self, rng, example_inputs) -> Dict[str, Any]:
        """Build the sharded-layout parameter tree. Blocks are initialized
        via ``vmap`` over per-layer keys → leading ``[L, ...]`` layer axis
        (the axis the engine shards over ``pipe``)."""
        tied: Dict[str, Any] = {}
        pre_params: List[Any] = []
        post_params: List[Any] = []
        x = example_inputs
        rngs = jax.random.split(rng, len(self._layers) + 1)

        def init_one(layer, key, x):
            if layer.tied_key is not None:
                if layer.tied_key not in tied:
                    tied[layer.tied_key] = layer.init(key, x)
                return {}
            return layer.init(key, x)

        for layer in self.pre_layers:
            p = init_one(layer, rngs[layer.index], x)
            pre_params.append(p)
            x = self._apply_layer(layer, p if layer.tied_key is None
                                  else tied[layer.tied_key], x)

        block0 = self.block_layers[0]
        block_keys = jax.random.split(rngs[block0.index], self.n_blocks)
        x_in = x
        blocks = jax.vmap(lambda k: block0.init(k, x_in))(block_keys)
        # activations flow through one block to type the postlude init
        x = self._apply_layer(
            block0, jax.tree_util.tree_map(lambda a: a[0], blocks), x)

        for layer in self.post_layers:
            p = init_one(layer, rngs[layer.index], x)
            post_params.append(p)
            x = self._apply_layer(layer, p if layer.tied_key is None
                                  else tied[layer.tied_key], x)
        self._output_shape = jax.tree_util.tree_map(jnp.shape, x)
        return {"pre": pre_params, "blocks": blocks,
                "post": post_params, "tied": tied}

    def _apply_layer(self, layer: _BuiltLayer, params, x, rngs=None):
        return layer.apply(params, x, rngs=rngs)

    # ------------------------------------------------------------------
    # engine-facing apply fns (pure; params subtree layouts as built above)
    def pre_apply(self, params, inputs, rngs=None):
        x = inputs
        for layer, p in zip(self.pre_layers, params["pre"]):
            actual = params["tied"][layer.tied_key] if layer.tied_key else p
            x = self._apply_layer(layer, actual, x, rngs=rngs)
        return x

    def block_apply(self, block_params, x, rngs=None):
        y = self._apply_layer(self.block_layers[0], block_params, x, rngs=rngs)
        return y

    def post_apply(self, params, x, rngs=None):
        for layer, p in zip(self.post_layers, params["post"]):
            actual = params["tied"][layer.tied_key] if layer.tied_key else p
            x = self._apply_layer(layer, actual, x, rngs=rngs)
        return x

    def topology(self):
        from deepspeed_tpu.parallel.topology import get_topology

        return get_topology()
