"""Pipeline instruction schedules.

Capability parity with the reference ``deepspeed/runtime/pipe/schedule.py``
(``TrainSchedule:182``, ``InferenceSchedule:129``, ``DataParallelSchedule:292``
and the ``PipeInstruction`` vocabulary). The reference *interprets* these
instruction lists imperatively (``pipe/engine.py:1359`` dispatch table); on
TPU the whole train schedule is compiled into one XLA program by
``runtime/pipe/engine.py`` — these classes remain the canonical description
of the schedule (used for validation, cost modeling, and by any future MPMD
multi-controller executor), and the compiled program is equivalent to
executing them.

The 1F1B clock construction: forward of micro-batch ``m`` at stage ``s``
happens at clock ``s + 2m``; backward at clock ``2(P-1) - s + 2m + 1``.
Forwards occupy clocks with parity ``s % 2`` and backwards the opposite
parity, so each stage alternates one-forward-one-backward in steady state,
and at most ``P - s`` forward activations are alive at stage ``s`` — the
1F1B memory profile.
"""

from typing import Iterator, List


class PipeInstruction:
    """A single step of work for one pipeline stage."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        inner = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({inner})"

    def __eq__(self, other):
        return (self.__class__ is other.__class__
                and self.kwargs == other.kwargs)

    def __hash__(self):
        return hash((self.__class__, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Apply the optimizer (all stages, end of batch)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce grads of tied (pipe-replicated) weights over the pipe axis."""


class BufferOpInstruction(PipeInstruction):
    """An instruction operating on a pipeline buffer slot."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """Stage 0: load micro-batch ``micro_batch_id`` into a buffer."""


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule:
    """Generator of per-clock instruction lists for one stage.

    Mirrors the reference ``PipeSchedule`` ABC surface: ``micro_batches``,
    ``stages``, ``stage_id``, ``steps()``, ``num_pipe_buffers()``.
    """

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        raise NotImplementedError

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, mb: int) -> bool:
        return 0 <= mb < self.micro_batches

    def __iter__(self):
        return self.steps()


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelined sweep (reference ``schedule.py:129``)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for clock in range(total):
            cmds: List[PipeInstruction] = []
            mb = clock - self.stage_id
            if self._valid_micro_batch(mb):
                buf = mb % self.num_pipe_buffers()
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf, micro_batch_id=mb))
                else:
                    cmds.append(RecvActivation(buf, micro_batch_id=mb))
                cmds.append(ForwardPass(buf, micro_batch_id=mb))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf, micro_batch_id=mb))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B training schedule (reference ``schedule.py:182``).

    Clock formulas (see module docstring): ``fwd(s, m) = s + 2m`` and
    ``bwd(s, m) = 2(P-1) - s + 2m + 1``. A send at clock ``c`` pairs with
    the neighbor's recv at clock ``c + 1``.
    """

    def num_pipe_buffers(self) -> int:
        return min(self.stages - self.stage_id, self.micro_batches)

    def _fwd_clock(self, mb: int) -> int:
        return self.stage_id + 2 * mb

    def _bwd_clock(self, mb: int) -> int:
        return 2 * (self.stages - 1) - self.stage_id + 2 * mb + 1

    def steps(self):
        P, M, s = self.stages, self.micro_batches, self.stage_id
        total_clocks = 2 * (M + P - 1)
        n_buf = self.num_pipe_buffers()
        for clock in range(total_clocks):
            cmds: List[PipeInstruction] = []
            # forward work this clock?
            mb_f = (clock - s) // 2 if (clock - s) % 2 == 0 else None
            if mb_f is not None and self._valid_micro_batch(mb_f) \
                    and self._fwd_clock(mb_f) == clock:
                buf = mb_f % n_buf
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf, micro_batch_id=mb_f))
                else:
                    cmds.append(RecvActivation(buf, micro_batch_id=mb_f))
                cmds.append(ForwardPass(buf, micro_batch_id=mb_f))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf, micro_batch_id=mb_f))
            # backward work this clock?
            rem = clock - (2 * (P - 1) - s + 1)
            mb_b = rem // 2 if rem >= 0 and rem % 2 == 0 else None
            if mb_b is not None and self._valid_micro_batch(mb_b) \
                    and self._bwd_clock(mb_b) == clock:
                buf = mb_b % n_buf
                if not self.is_last_stage:
                    cmds.append(RecvGrad(buf, micro_batch_id=mb_b))
                cmds.append(BackwardPass(buf, micro_batch_id=mb_b))
                if not self.is_first_stage:
                    cmds.append(SendGrad(buf, micro_batch_id=mb_b))
            # final clock: reductions + step
            if clock == total_clocks - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference ``schedule.py:292``)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            cmds = [LoadMicroBatch(0, micro_batch_id=mb),
                    ForwardPass(0, micro_batch_id=mb),
                    BackwardPass(0, micro_batch_id=mb)]
            if mb == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds
