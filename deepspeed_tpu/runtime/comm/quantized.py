"""Int8 quantized all-reduce — the EQuARX-style middle tier.

Between the dense bf16/f32 carrier and the 1-bit sign wire
(``runtime/comm/compressed.py``) sits the int8 tier (EQuARX,
arxiv 2506.17615): symmetric per-chunk scales, quantize around *both* legs
of a reduce-scatter + all-gather decomposition so every collective operand
on the wire is int8:

1. **scatter leg**: each replica splits its tensor into ``world`` equal
   chunks, quantizes them (``ops/quantizer.py`` chunked symmetric int8),
   and an ``all_to_all`` routes chunk *i* of every replica to replica *i*
   — int8 payload, f32 scales riding along at 1/group_size density.
2. **local reduce**: replica *i* dequantizes the ``world`` copies of its
   chunk and accumulates them left-to-right (the same association XLA's
   all-reduce uses, so the ``"none"``/dense tier through this module is
   bit-identical to a raw psum).
3. **gather leg**: the reduced chunk is re-quantized and an ``all_gather``
   (int8 again) reassembles the full tensor on every replica.

Wire cut vs a bf16 dense all-reduce: 2× per element, plus the scales
overhead (4/group_size per element). Unlike the 1-bit tier there is no
error-feedback state — int8 round-off on gradients is small enough that
the reference (and EQuARX) run it stateless.

Must run inside ``shard_map``/``pmap`` where ``axis_name`` is bound;
``axis_size`` is the static member count (collective layouts depend on it
at trace time, so it cannot be read from a traced ``psum(1)``).
"""

import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.ops.quantizer import dequantize_chunks, quantize_chunks

COMM_DTYPES = ("none", "int8", "1bit")


def int8_wire_bytes(n_elements: int, axis_size: int,
                    group_size: int = 1024) -> int:
    """Per-member collective operand bytes of :func:`int8_allreduce` —
    the wire-true size a comms log must record (NOT the logical f32
    size). Mirrors the padding/chunking arithmetic below exactly: scatter
    leg = full padded int8 tensor + f32 scales, gather leg = one reduced
    chunk + its scales. The HLO regression test pins this formula against
    the compiled program's collective operands."""
    if axis_size <= 1:
        return 0
    chunk = -(-n_elements // axis_size)
    chunk = -(-chunk // group_size) * group_size
    padded = chunk * axis_size
    scatter = padded + (padded // group_size) * 4   # all_to_all: q + scales
    gather = chunk + (chunk // group_size) * 4      # all_gather: q + scales
    return scatter + gather


def int8_allreduce(x, axis_name, axis_size: int, group_size: int = 1024,
                   mean: bool = True):
    """Quantized mean/sum-allreduce of ``x`` over ``axis_name``.

    Both wire legs carry int8 (module docstring). Returns f32 in ``x``'s
    shape. ``axis_size == 1`` short-circuits (nothing to reduce, and a
    quantize round-trip would add error for no wire win).
    """
    if axis_size == 1:
        return x.astype(jnp.float32)
    flat = x.reshape(-1).astype(jnp.float32)
    orig = flat.size
    # each member owns one equal, group-aligned chunk
    chunk = -(-orig // axis_size)
    chunk = -(-chunk // group_size) * group_size
    pad = chunk * axis_size - orig
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, scales = quantize_chunks(flat, group_size)
    q = q.reshape(axis_size, chunk)
    scales = scales.reshape(axis_size, chunk // group_size)
    # scatter leg: row j of every member lands on member j
    q_t = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s_t = lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0)
    partial = dequantize_chunks(q_t[0], s_t[0], group_size)
    for i in range(1, axis_size):
        partial = partial + dequantize_chunks(q_t[i], s_t[i], group_size)
    if mean:
        partial = partial / axis_size
    # gather leg: requantize the reduced chunk, reassemble everywhere
    q2, s2 = quantize_chunks(partial, group_size)
    q_full = lax.all_gather(q2, axis_name, axis=0, tiled=True)
    s_full = lax.all_gather(s2, axis_name, axis=0, tiled=True)
    out = dequantize_chunks(q_full, s_full, group_size, size=orig)
    return out.reshape(x.shape)


def dense_allreduce(x, axis_name, axis_size: int, mean: bool = True):
    """Full-width psum, shape-preserving — the ``"none"`` tier, kept here
    so the bucketed dispatch treats every tier uniformly."""
    out = lax.psum(x, axis_name)
    if mean:
        out = out / axis_size
    return out
