"""Error-feedback compressed collectives (1-bit family).

Capability parity with the reference compressed-allreduce backends
(``runtime/comm/nccl.py:51`` ``compressed_allreduce``: 1-bit sign
compression with worker+server error feedback over NCCL igather/scatter,
and the CUDA-aware MPI variant in ``runtime/comm/mpi.py``).

TPU-native form: compression is a *math transform around a psum*. Inside a
``shard_map`` over the ``data`` axis each replica holds its local tensor;
``compressed_allreduce`` corrects it with the carried error, reduces it to
sign × mean-|x| (a 32× wire-size cut on DCN — on-chip ICI rarely needs it,
cross-pod DCN does), averages the compressed values with ``lax.psum``, and
returns the new local error. No igather/scatter choreography: the XLA
collective handles layout.
"""

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


def onebit_compress(x: jnp.ndarray, error: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit compression with error feedback.

    Returns ``(compressed, new_error)`` where ``compressed = scale *
    sign(x + error)``, ``scale = mean(|x + error|)`` (the L1/N scale the
    reference server uses), and ``new_error = corrected - compressed``.
    """
    corrected = x + error
    scale = jnp.mean(jnp.abs(corrected))
    compressed = scale * jnp.sign(corrected)
    return compressed, corrected - compressed


def compressed_allreduce(x: jnp.ndarray, error: jnp.ndarray, axis_name: str
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean-allreduce of 1-bit-compressed tensors over ``axis_name``.

    Must run inside ``shard_map``/``pmap`` where ``axis_name`` is bound.
    Wire format is sign ± one scalar scale per tensor; the mean of the
    compressed replicas is what lands on every replica (the reference's
    server-side averaging of worker signs).
    """
    compressed, new_error = onebit_compress(x, error)
    n = jax.lax.psum(1, axis_name)
    avg = jax.lax.psum(compressed, axis_name) / n
    return avg, new_error


def init_error_tree(params, dp: int):
    """Zero error-feedback state: one slice per data-parallel replica.

    Leaves are stacked ``[dp, *leaf.shape]`` so each replica owns row
    ``axis_index`` under ``shard_map`` — errors legitimately differ per
    replica and must not be treated as replicated.
    """
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros((dp,) + p.shape, p.dtype), params)


def make_compressed_grad_fn(loss_fn, mesh, data_axis: str = "data"):
    """Wrap a loss fn so grads are averaged with 1-bit compression.

    Returns ``fn(params, batch, error_tree) -> (loss, grads, new_error_tree)``
    jit-compatible over ``mesh``; params replicated, batch sharded over the
    data axis, and the error tree stacked per replica (see
    :func:`init_error_tree`) and sharded over the data axis — error feedback
    is per-replica state. This is the plumbing 1-bit optimizers use
    post-warmup.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def local_step(params, batch, errors):
        # errors arrive as this replica's [1, ...] slice of the stack
        errors = jax.tree_util.tree_map(lambda e: e[0], errors)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_e = treedef.flatten_up_to(errors)
        out_g, out_e = [], []
        for g, e in zip(flat_g, flat_e):
            avg, ne = compressed_allreduce(g, e, data_axis)
            out_g.append(avg)
            out_e.append(ne[None])  # restack the per-replica row
        n = jax.lax.psum(1, data_axis)
        loss = jax.lax.psum(loss, data_axis) / n
        return (loss,
                jax.tree_util.tree_unflatten(treedef, out_g),
                jax.tree_util.tree_unflatten(treedef, out_e))

    def wrapped(params, batch, errors):
        err_specs = jax.tree_util.tree_map(lambda _: P(data_axis), errors)
        return shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(data_axis), err_specs),
            out_specs=(P(), P(), err_specs),
            check_vma=False)(params, batch, errors)

    return wrapped
