"""Error-feedback compressed collectives (1-bit family).

Capability parity with the reference compressed-allreduce backends
(``runtime/comm/nccl.py:51`` ``compressed_allreduce``: 1-bit sign
compression with worker+server error feedback over NCCL igather/scatter,
and the CUDA-aware MPI variant in ``runtime/comm/mpi.py``).

TPU-native form: compression is a *math transform around a collective*.
Inside a ``shard_map`` over the ``data`` axis each replica holds its local
tensor; ``compressed_allreduce`` corrects it with the carried error and
reduces it to sign × mean-|x|. Two wire carriers exist:

- ``carrier="packed"`` (default, wire-true): sign bits are packed 8-per-byte
  into a ``uint8`` bitfield and exchanged with an **all-gather of packed
  worker signs + one f32 scale per tensor** — the collective operand is
  uint8, so the DCN payload really is 1/32 of the f32 tensor (the
  reference's igather of sign bytes, minus the byte-per-sign waste). Every
  replica then reconstructs the server-style mean of signs locally.
- ``carrier="dense"``: the sign×scale tensor is psum'd at full f32 width —
  the compression is numerical only, not a wire cut. Kept as the reference
  semantics baseline; the packed carrier reproduces its trajectories
  bit-for-bit (reconstruction accumulates worker contributions
  left-to-right, the same association XLA's all-reduce applies).

Both carriers share one compression rule: ``sign(x) = +1 if x >= 0 else
-1``. A packed bitfield has no zero symbol, so the dense carrier uses the
same convention — otherwise the two would diverge on exact zeros and the
bit-parity contract between them would be unverifiable.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

CARRIERS = ("packed", "dense")


def onebit_wire_bytes(n_elements: int, carrier: str = "packed") -> int:
    """Per-member collective operand bytes of :func:`compressed_allreduce`
    — the wire-true size a comms log must record. Packed carrier: the
    uint8 sign bitfield + one f32 scale per tensor (all-gather operands);
    dense carrier: the full f32 sign×scale tensor (psum operand)."""
    if carrier == "packed":
        return -(-n_elements // 8) + 4
    return n_elements * 4


# ----------------------------------------------------------------------
# uint8 bitfield packing (jnp.packbits-equivalent via shift/or lanes)
def pack_signs(x: jnp.ndarray) -> jnp.ndarray:
    """Pack the sign bits of ``x`` (+ = 1, - = 0) into a flat uint8
    bitfield, least-significant bit first, zero-padded to a lane multiple
    of 8. Returns ``uint8[ceil(x.size / 8)]``."""
    flat = x.reshape(-1)
    bits = jnp.where(flat >= 0, jnp.uint8(1), jnp.uint8(0))
    pad = (-bits.size) % 8
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros((pad,), jnp.uint8)])
    lanes = bits.reshape(-1, 8)
    packed = lanes[:, 0]
    for i in range(1, 8):
        packed = packed | (lanes[:, i] << np.uint8(i))
    return packed


def unpack_signs(packed: jnp.ndarray, n: int,
                 dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`pack_signs`: flat ``±1`` vector of length ``n``
    from a uint8 bitfield (padding bits discarded)."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    return bits.reshape(-1)[:n].astype(dtype) * 2 - 1


def _sign(x: jnp.ndarray) -> jnp.ndarray:
    """Zero-free sign: ±1 with sign(0) = +1 (the packable convention)."""
    return jnp.where(x >= 0, jnp.float32(1), jnp.float32(-1))


def onebit_compress(x: jnp.ndarray, error: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit compression with error feedback.

    Returns ``(compressed, new_error)`` where ``compressed = scale *
    sign(x + error)``, ``scale = mean(|x + error|)`` (the L1/N scale the
    reference server uses), and ``new_error = corrected - compressed``.
    """
    corrected = x + error
    scale = jnp.mean(jnp.abs(corrected))
    compressed = scale * _sign(corrected)
    return compressed, corrected - compressed


def packed_onebit_allreduce(x: jnp.ndarray, error: jnp.ndarray,
                            axis_name) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Wire-true 1-bit mean-allreduce: all-gather of packed uint8 worker
    signs + per-tensor f32 scales, then server-style mean-of-signs
    reconstruction on every replica.

    Bit-parity with the dense carrier: each worker's contribution is
    ``scale_i * (±1)`` — exactly the float the dense carrier psums — and
    the reconstruction accumulates workers left-to-right, matching the
    all-reduce association, so the result is bit-identical to
    ``psum(scale * sign) / n``.
    """
    corrected = x + error
    scale = jnp.mean(jnp.abs(corrected))
    compressed = scale * _sign(corrected)
    new_error = corrected - compressed
    wire = pack_signs(corrected)                       # uint8[ceil(n/8)]
    signs = lax.all_gather(wire, axis_name, axis=0)    # uint8[w, ceil(n/8)]
    scales = lax.all_gather(scale, axis_name, axis=0)  # f32[w]
    world = signs.shape[0]
    total = scales[0] * unpack_signs(signs[0], x.size)
    for i in range(1, world):
        total = total + scales[i] * unpack_signs(signs[i], x.size)
    return (total / world).reshape(x.shape), new_error


def compressed_allreduce(x: jnp.ndarray, error: jnp.ndarray, axis_name,
                         carrier: str = "packed"
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean-allreduce of 1-bit-compressed tensors over ``axis_name``.

    Must run inside ``shard_map``/``pmap`` where ``axis_name`` is bound.
    ``carrier`` picks the wire format (module docstring): ``"packed"``
    exchanges uint8 bitfields + scales, ``"dense"`` psums the sign×scale
    tensor at full width. The error-feedback semantics (and, by
    construction, the trajectories) are identical.
    """
    if carrier not in CARRIERS:
        raise ValueError(f"carrier must be one of {CARRIERS}, got {carrier!r}")
    if carrier == "packed":
        return packed_onebit_allreduce(x, error, axis_name)
    compressed, new_error = onebit_compress(x, error)
    n = lax.psum(1, axis_name)
    avg = lax.psum(compressed, axis_name) / n
    return avg, new_error


def init_error_tree(params, dp: int):
    """Zero error-feedback state: one slice per data-parallel replica.

    Leaves are stacked ``[dp, *leaf.shape]`` so each replica owns row
    ``axis_index`` under ``shard_map`` — errors legitimately differ per
    replica and must not be treated as replicated.
    """
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros((dp,) + p.shape, p.dtype), params)


def make_compressed_grad_fn(loss_fn, mesh, data_axis: str = "data",
                            carrier: str = "packed"):
    """Wrap a loss fn so grads are averaged with 1-bit compression.

    Returns ``fn(params, batch, error_tree) -> (loss, grads, new_error_tree)``
    jit-compatible over ``mesh``; params replicated, batch sharded over the
    data axis, and the error tree stacked per replica (see
    :func:`init_error_tree`) and sharded over the data axis — error feedback
    is per-replica state. This is the plumbing 1-bit optimizers use
    post-warmup.
    """
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.utils.compat import shard_map

    def local_step(params, batch, errors):
        # errors arrive as this replica's [1, ...] slice of the stack
        errors = jax.tree_util.tree_map(lambda e: e[0], errors)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_e = treedef.flatten_up_to(errors)
        out_g, out_e = [], []
        for g, e in zip(flat_g, flat_e):
            avg, ne = compressed_allreduce(g, e, data_axis, carrier=carrier)
            out_g.append(avg)
            out_e.append(ne[None])  # restack the per-replica row
        n = jax.lax.psum(1, data_axis)
        loss = jax.lax.psum(loss, data_axis) / n
        return (loss,
                jax.tree_util.tree_unflatten(treedef, out_g),
                jax.tree_util.tree_unflatten(treedef, out_e))

    def wrapped(params, batch, errors):
        err_specs = jax.tree_util.tree_map(lambda _: P(data_axis), errors)
        return shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(data_axis), err_specs),
            out_specs=(P(), P(), err_specs),
            check_vma=False)(params, batch, errors)

    return wrapped
