"""1-bit Adam.

Capability parity with the reference ``OnebitAdam``
(``runtime/fp16/onebit/adam.py:10``; https://arxiv.org/abs/2102.02888):

- **Warmup stage** (step < ``freeze_step``): exact Adam with full-precision
  gradient averaging.
- **Compressed stage**: the second moment ``v`` is frozen at its warmup
  value; each replica updates its *local* momentum with its *local* grads,
  the momentum is 1-bit-compressed with per-replica error feedback and
  mean-allreduced (sign × scale over the wire), and the averaged momentum
  drives the update against the frozen ``v``.

TPU-native packaging: a pure ``init/update_local`` pair. ``update_local``
runs inside ``shard_map`` over the data axis (local grads in, collective
inside). The stage is a **static** Python flag — the caller (engine)
recompiles once when crossing ``freeze_step`` so the compiled graph carries
exactly one collective: a full psum during warmup, a 1-bit psum after.
The reference's NCCL/MPI gather-scatter choreography is that one psum.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce


class OnebitAdamState(NamedTuple):
    m: Any            # momentum (local in compressed stage)
    v: Any            # second moment (frozen after freeze_step)
    error: Any        # per-replica compression error feedback
    step: jnp.ndarray


def _map2(fn, treedef, *trees):
    flats = [treedef.flatten_up_to(t) for t in trees]
    outs = [fn(*leaves) for leaves in zip(*flats)]
    n_out = len(outs[0])
    return tuple(jax.tree_util.tree_unflatten(treedef, [o[i] for o in outs])
                 for i in range(n_out))


class OnebitAdam:
    """Engine-compatible optimizer (``init``/``update_local`` surface)."""

    name = "onebitadam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=100000, data_axis="data",
                 carrier="packed", **_unused):
        self.lr = float(lr)
        self.b1, self.b2 = betas
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.data_axis = data_axis
        # wire carrier of the compressed stage: "packed" = uint8 bitfield
        # all-gather (wire-true), "dense" = f32 psum of sign x scale
        self.carrier = carrier

    def init(self, params) -> OnebitAdamState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return OnebitAdamState(m=zeros(), v=zeros(), error=zeros(),
                               step=jnp.zeros((), jnp.int32))

    def update_local(self, local_grads, state: OnebitAdamState, params,
                     lr=None, compressed: bool = False
                     ) -> Tuple[Any, OnebitAdamState]:
        """One step from per-replica grads; call inside shard_map with the
        data axis bound. ``compressed`` is static: False → warmup Adam
        (full-precision psum), True → 1-bit stage."""
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        bias1 = 1 - b1 ** step.astype(jnp.float32)
        bias2 = 1 - b2 ** step.astype(jnp.float32)

        def leaf(g, m, v, e, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if compressed:
                m_local = b1 * m + (1 - b1) * g
                m_new, e_new = compressed_allreduce(
                    m_local, e, self.data_axis, carrier=self.carrier)
                v_new = v  # frozen
            else:
                n = jax.lax.psum(1, self.data_axis)
                g_avg = jax.lax.psum(g, self.data_axis) / n
                m_new = b1 * m + (1 - b1) * g_avg
                v_new = b2 * v + (1 - b2) * g_avg * g_avg
                e_new = e
            upd = (m_new / bias1) / (jnp.sqrt(v_new / bias2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m_new, v_new, e_new

        _, treedef = jax.tree_util.tree_flatten(local_grads)
        new_p, new_m, new_v, new_e = _map2(
            leaf, treedef, local_grads, state.m, state.v, state.error, params)
        return new_p, OnebitAdamState(m=new_m, v=new_v, error=new_e,
                                      step=step)
