"""0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py``;
https://arxiv.org/abs/2202.06009): compressed communication from step one —
no full-precision warmup. Gradients are 1-bit compressed with error
feedback every step; the variance re-synchronizes at full precision on a
periodic interval (the reference's adaptive ``var_update_scaler`` schedule,
exposed here as ``var_sync_interval``). ``sync`` is a static flag — the
caller alternates between the two cached compilations.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce
from deepspeed_tpu.runtime.fp16.onebit.adam import _map2


class ZeroOneAdamState(NamedTuple):
    m: Any
    v: Any
    error: Any
    step: jnp.ndarray


class ZeroOneAdam:
    name = "zerooneadam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, var_sync_interval=16, data_axis="data",
                 carrier="packed", **_unused):
        self.lr = float(lr)
        self.b1, self.b2 = betas
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.var_sync_interval = int(var_sync_interval)
        self.data_axis = data_axis
        self.carrier = carrier

    def init(self, params) -> ZeroOneAdamState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return ZeroOneAdamState(m=zeros(), v=zeros(), error=zeros(),
                                step=jnp.zeros((), jnp.int32))

    def update_local(self, local_grads, state: ZeroOneAdamState, params,
                     lr=None, sync: bool = False
                     ) -> Tuple[Any, ZeroOneAdamState]:
        """``sync=True`` adds the periodic full-precision variance re-sync
        psum; otherwise the only collective is the 1-bit psum."""
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        bias1 = 1 - b1 ** step.astype(jnp.float32)
        bias2 = 1 - b2 ** step.astype(jnp.float32)

        def leaf(g, m, v, e, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            g_comp, e_new = compressed_allreduce(
                g, e, self.data_axis, carrier=self.carrier)
            if sync:
                n = jax.lax.psum(1, self.data_axis)
                g_for_v = jax.lax.psum(g, self.data_axis) / n
            else:
                g_for_v = g_comp
            m_new = b1 * m + (1 - b1) * g_comp
            v_new = b2 * v + (1 - b2) * g_for_v * g_for_v
            upd = (m_new / bias1) / (jnp.sqrt(v_new / bias2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m_new, v_new, e_new

        _, treedef = jax.tree_util.tree_flatten(local_grads)
        new_p, new_m, new_v, new_e = _map2(
            leaf, treedef, local_grads, state.m, state.v, state.error, params)
        return new_p, ZeroOneAdamState(m=new_m, v=new_v, error=new_e,
                                       step=step)
