"""1-bit LAMB (reference ``runtime/fp16/onebit/lamb.py``;
https://arxiv.org/abs/2104.06069): 1-bit Adam's compressed-momentum scheme
plus LAMB's per-layer trust ratio. During the compressed stage the trust
ratio is frozen at its last warmup value (the reference freezes its fused
lamb coefficients), so no extra full-precision collectives are needed.
``compressed`` is a static flag — one collective per compiled graph.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce
from deepspeed_tpu.runtime.fp16.onebit.adam import _map2


class OnebitLambState(NamedTuple):
    m: Any
    v: Any
    error: Any
    frozen_ratio: Any   # per-leaf trust ratio recorded during warmup
    step: jnp.ndarray


class OnebitLamb:
    name = "onebitlamb"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=100000, data_axis="data",
                 max_coeff=10.0, min_coeff=0.01, carrier="packed",
                 **_unused):
        self.lr = float(lr)
        self.b1, self.b2 = betas
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.data_axis = data_axis
        self.max_coeff = float(max_coeff)
        self.min_coeff = float(min_coeff)
        self.carrier = carrier

    def init(self, params) -> OnebitLambState:
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        ones = jax.tree_util.tree_map(lambda p: jnp.ones((), jnp.float32),
                                      params)
        return OnebitLambState(m=zeros(), v=zeros(), error=zeros(),
                               frozen_ratio=ones,
                               step=jnp.zeros((), jnp.int32))

    def update_local(self, local_grads, state: OnebitLambState, params,
                     lr=None, compressed: bool = False
                     ) -> Tuple[Any, OnebitLambState]:
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        bias1 = 1 - b1 ** step.astype(jnp.float32)
        bias2 = 1 - b2 ** step.astype(jnp.float32)

        def leaf(g, m, v, e, fr, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if compressed:
                m_local = b1 * m + (1 - b1) * g
                m_new, e_new = compressed_allreduce(
                    m_local, e, self.data_axis, carrier=self.carrier)
                v_new = v
            else:
                n = jax.lax.psum(1, self.data_axis)
                g_avg = jax.lax.psum(g, self.data_axis) / n
                m_new = b1 * m + (1 - b1) * g_avg
                v_new = b2 * v + (1 - b2) * g_avg * g_avg
                e_new = e
            upd = (m_new / bias1) / (jnp.sqrt(v_new / bias2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            if compressed:
                ratio = fr
                fr_new = fr
            else:
                w_norm = jnp.linalg.norm(p32.reshape(-1))
                u_norm = jnp.linalg.norm(upd.reshape(-1))
                ratio = jnp.where(
                    (w_norm > 0) & (u_norm > 0),
                    jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                    1.0)
                fr_new = ratio
            return ((p32 - lr * ratio * upd).astype(p.dtype),
                    m_new, v_new, e_new, fr_new)

        _, treedef = jax.tree_util.tree_flatten(local_grads)
        new_p, new_m, new_v, new_e, new_fr = _map2(
            leaf, treedef, local_grads, state.m, state.v, state.error,
            state.frozen_ratio, params)
        return new_p, OnebitLambState(m=new_m, v=new_v, error=new_e,
                                      frozen_ratio=new_fr, step=step)
