"""1-bit optimizers (reference ``deepspeed/runtime/fp16/onebit/``)."""

from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam, OnebitAdamState
from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLamb
from deepspeed_tpu.runtime.fp16.onebit.zoadam import ZeroOneAdam

__all__ = ["OnebitAdam", "OnebitAdamState", "OnebitLamb", "ZeroOneAdam"]
