"""Loss scaling (reference ``deepspeed/runtime/fp16/loss_scaler.py``:
``LossScaler`` static, ``DynamicLossScaler:77``).

TPU-native: scaler *state* is a small pytree updated inside the jitted step
with ``lax.cond`` — no host sync for the overflow check (the reference pays a
``.item()`` device→host round-trip per step; here skip/update compile into
the step). Static policy knobs live in :class:`LossScalerConfig` (closed over
by the step function, not traced).
"""

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LossScalerConfig:
    dynamic: bool = False
    scale_window: int = 1000
    scale_factor: float = 2.0
    min_scale: float = 1.0
    max_hysteresis: int = 2


class LossScaleState(NamedTuple):
    loss_scale: jnp.ndarray   # f32 scalar
    good_steps: jnp.ndarray   # i32 scalar, steps since last overflow
    hysteresis: jnp.ndarray   # i32 scalar, remaining tolerated overflows


def create_loss_scaler(static_loss_scale: float = 1.0,
                       dynamic: bool = False,
                       initial_scale: float = 2.0**16,
                       scale_window: int = 1000,
                       scale_factor: float = 2.0,
                       min_scale: float = 1.0,
                       hysteresis: int = 2):
    """Returns ``(config, state)``."""
    config = LossScalerConfig(dynamic=dynamic, scale_window=scale_window,
                              scale_factor=scale_factor, min_scale=min_scale,
                              max_hysteresis=hysteresis)
    scale = initial_scale if dynamic else static_loss_scale
    state = LossScaleState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32))
    return config, state


def has_inf_or_nan(tree) -> jnp.ndarray:
    """Overflow probe over a grad pytree (reference ``_has_inf_or_nan``,
    ``stage_1_and_2.py:1966``)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(False)
    flags = [~jnp.isfinite(l.astype(jnp.float32)).all() for l in leaves]
    return jnp.stack(flags).any()


def update_scale(config: LossScalerConfig, state: LossScaleState,
                 overflow: jnp.ndarray) -> LossScaleState:
    """Post-step scale adjustment (reference ``DynamicLossScaler.update_scale``)."""
    if not config.dynamic:
        return state

    def on_overflow(s):
        new_hyst = s.hysteresis - 1
        drop = new_hyst <= 0
        new_scale = jnp.where(
            drop, jnp.maximum(s.loss_scale / config.scale_factor, config.min_scale),
            s.loss_scale)
        return LossScaleState(loss_scale=new_scale,
                              good_steps=jnp.asarray(0, jnp.int32),
                              hysteresis=jnp.maximum(new_hyst, 0))

    def on_good(s):
        grow = (s.good_steps + 1) % config.scale_window == 0
        new_scale = jnp.where(grow, s.loss_scale * config.scale_factor, s.loss_scale)
        return LossScaleState(loss_scale=new_scale,
                              good_steps=s.good_steps + 1,
                              hysteresis=jnp.asarray(config.max_hysteresis, jnp.int32))

    return jax.lax.cond(overflow, on_overflow, on_good, state)
