"""Hessian top-eigenvalue estimation (drives the MoQ quantization schedule).

Capability parity with the reference ``Eigenvalue`` (``runtime/eigenvalue.py:7``),
which runs power iteration using ``torch.autograd.grad`` double-backward per
layer. The TPU-native version uses JAX's forward-over-reverse
Hessian-vector product (``jvp`` of ``grad``) inside one jitted power-iteration
loop — no graph retention tricks, and the whole iteration compiles to a
single XLA program with a ``lax.fori_loop``.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def _normalize(tree):
    sq = sum(jnp.vdot(l, l).real for l in jax.tree_util.tree_leaves(tree))
    norm = jnp.sqrt(sq)
    safe = jnp.maximum(norm, 1e-12)
    return jax.tree_util.tree_map(lambda l: l / safe, tree), norm


class Eigenvalue:
    def __init__(self,
                 verbose: bool = False,
                 max_iter: int = 100,
                 tol: float = 1e-2,
                 stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "",
                 layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def compute_eigenvalue(self, loss_fn: Callable, params, batch,
                           rng=None, block_paths: Optional[Dict] = None):
        """Top Hessian eigenvalue per parameter block.

        ``loss_fn(params, batch) -> scalar``. ``block_paths``: optional
        ``{name: subtree_selector}`` mapping; default treats each top-level
        key of ``params`` as a block (the reference iterates layers of
        ``module.named_modules()`` matching ``layer_name``).
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if block_paths is None:
            block_paths = {k: k for k in params} if isinstance(params, dict) \
                else {"all": None}

        results = {}
        for name, key in block_paths.items():
            sub = params[key] if key is not None else params
            results[name] = float(self._power_iterate(
                loss_fn, params, batch, key, sub, rng))
            if self.verbose:
                logger.info(f"eigenvalue[{name}] = {results[name]:.4e}")
        return results

    def _power_iterate(self, loss_fn, params, batch, key, sub, rng):
        def loss_wrt_block(block):
            if key is None:
                return loss_fn(block, batch)
            merged = dict(params)
            merged[key] = block
            return loss_fn(merged, batch)

        grad_fn = jax.grad(loss_wrt_block)

        def hvp(v):
            return jax.jvp(grad_fn, (sub,), (v,))[1]

        leaves, treedef = jax.tree_util.tree_flatten(sub)
        keys = jax.random.split(rng, len(leaves))
        # tangents must match the primal dtypes (jvp rejects fp32 tangents
        # against bf16/fp16 params — exactly the mixed-precision configs
        # MoQ targets)
        v0 = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, l.dtype)
                      for k, l in zip(keys, leaves)])
        v0, _ = _normalize(v0)

        @jax.jit
        def iterate(v):
            def cond(carry):
                i, _, lam, prev = carry
                converged = jnp.abs(lam - prev) <= self.tol * jnp.maximum(
                    jnp.abs(lam), 1e-12)
                return (i < self.max_iter) & ((i < 2) | ~converged)

            def body(carry):
                i, v, lam, _ = carry
                hv = hvp(v)
                v, new_lam = _normalize(hv)
                return i + 1, v, new_lam, lam

            _, _, lam, _ = jax.lax.while_loop(
                cond, body, (0, v, jnp.zeros(()), jnp.full((), jnp.inf)))
            return lam

        lam = iterate(v0)
        # reference semantics: a failed/zero estimate reports the stability
        # floor rather than 0 so the MoQ schedule never divides by zero
        return jnp.maximum(lam, self.stability)
