"""Master DeepSpeed-style JSON config.

Capability parity with the reference ``deepspeed/runtime/config.py``
(``DeepSpeedConfig``, batch-size triangle at ``:918-989``, ~70 ``get_*``
helpers), re-based on a pydantic tree plus a TPU-native ``mesh`` section that
declares named mesh axis sizes (data/fsdp/tp/pipe/expert/seq; ``model`` is
the deprecated alias of ``tp``) instead of the reference's implicit
world-size + mpu plumbing.
"""

import json
import os
from typing import Any, Dict, Optional

from pydantic import Field, model_validator

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (
    DeepSpeedConfigModel,
    dict_raise_error_on_duplicate_keys,
)
from deepspeed_tpu.runtime.precision_config import AMPConfig, BF16Config, FP16Config
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class MeshConfig(DeepSpeedConfigModel):
    """TPU-native: named mesh axis sizes. ``data`` may be -1 (fill remaining
    devices). The reference derives parallel dims from world size + an external
    mpu (``deepspeed/utils/groups.py``); here the mesh is declared.

    The 3-axis training/serving layout is ``{data: D, fsdp: F, tp: T}``
    (SpecLayout, ``runtime/zero/partition.py``): ``fsdp`` shards
    weights/optimizer state beyond the data axis (never the batch), ``tp``
    shards weight dims per parameter family. ``model`` is the accepted
    pre-3-axis alias for ``tp``."""

    data: int = -1
    fsdp: int = 1
    tp: int = 1
    # deprecated alias for tp (pre-3-axis-mesh configs); folded into tp
    # by the validator below
    model: int = Field(1, json_schema_extra={"deprecated": "alias of tp"})
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    axis_order: tuple = ("pipe", "data", "fsdp", "expert", "seq", "tp")
    # multi-slice/multi-pod: per-axis factor that crosses the DCN (slice)
    # boundary, e.g. {"data": 4} trains 4 pods data-parallel with all other
    # axes riding ICI inside each pod (reference: multinode NCCL topology;
    # here jax mesh_utils.create_hybrid_device_mesh places the axes)
    dcn: dict = Field(default_factory=dict)

    @model_validator(mode="after")
    def _fold_model_alias(self):
        if self.model != 1:
            if self.tp not in (1, self.model):
                raise ValueError(
                    f"mesh names both tp={self.tp} and its deprecated "
                    f"alias model={self.model} with different sizes — "
                    "keep only tp")
            # object.__setattr__: plain assignment would re-enter this
            # validator via validate_assignment
            object.__setattr__(self, "tp", self.model)
        return self


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference ``runtime/activation_checkpointing/config.py``. On TPU this
    selects a ``jax.checkpoint`` (remat) policy; partition_activations maps to
    sharding the saved residuals over the model axis."""

    # TPU-native extensions: presence of the config section enables remat
    # (set ``enabled: false`` to override); ``policy`` picks the
    # jax.checkpoint granularity — "full" recomputes whole blocks, "dots"
    # saves matmul outputs and recomputes only elementwise chains
    enabled: bool = True
    policy: str = "full"
    # reference checkpointing.py:372 — saved inter-layer residuals get a
    # sharding constraint spreading seq over the model axis (stored
    # sharded, all-gathered at recompute)
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False  # INERT: engine warns
    # reference checkpointing.py:485 — saved inter-layer residuals are
    # host-offloaded via a save_and_offload_only_these_names remat policy
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None  # INERT: engine warns
    synchronize_checkpoint_boundary: bool = False  # INERT: engine warns
    profile: bool = False  # INERT: engine warns


class CommsLoggerConfig(DeepSpeedConfigModel):
    """Reference ``deepspeed/comm/config.py``."""

    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = Field(default_factory=list)


class FlopsProfilerConfig(DeepSpeedConfigModel):
    """Reference ``deepspeed/profiling/config.py``."""

    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class MonitorConfig(DeepSpeedConfigModel):
    """Reference ``deepspeed/monitor/config.py`` (flattened sections)."""

    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)

    @property
    def enabled(self):
        return self.tensorboard.enabled or self.wandb.enabled or self.csv_monitor.enabled


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"

    @model_validator(mode="after")
    def _check_tag_validation(self):
        from deepspeed_tpu.runtime.constants import CHECKPOINT_TAG_VALIDATION_MODES

        normalized = self.tag_validation.capitalize()
        if normalized not in CHECKPOINT_TAG_VALIDATION_MODES:
            raise ValueError(
                f"checkpoint.tag_validation must be one of {CHECKPOINT_TAG_VALIDATION_MODES}, "
                f"got {self.tag_validation!r}")
        if normalized != self.tag_validation:
            object.__setattr__(self, "tag_validation", normalized)
        return self

    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: dict = Field(default_factory=dict)
    async_save: bool = False  # TPU-native: orbax-style async checkpointing
    # sharded: each host writes only its addressable shards (orbax/tensorstore
    # parallel write) — no consolidation, and restore can re-shard onto a
    # different mesh (the universal-checkpoint capability, reference
    # checkpoint/universal_checkpoint.py:13). False = consolidated npz.
    sharded: bool = False


class NebulaConfig(DeepSpeedConfigModel):
    """``nebula`` section (reference ``nebula/config.py``): service-style
    tiered checkpointing — fast-tier commits + periodic durable mirror
    with version retention. Served by ``TieredCheckpointEngine``."""

    enabled: bool = False
    persistent_storage_path: Optional[str] = None
    persistent_time_interval: float = 100.0
    num_of_version_in_retention: int = 2
    enable_nebula_load: bool = True
    load_path: Optional[str] = None


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class CommQuantizationConfig(DeepSpeedConfigModel):
    """``comm_quantization`` section: wire format of the gradient-reduction
    collectives (TPU-native; the reference's nearest knob is
    ``communication_data_type`` plus the 1-bit optimizer family).

    - ``dtype``: ``"none"`` keeps the full-width carrier (bucketing still
      applies), ``"int8"`` runs the EQuARX-style two-leg quantized
      allreduce (``runtime/comm/quantized.py``), ``"1bit"`` selects the
      packed sign wire — valid only with a 1-bit optimizer, whose state
      carries the error feedback.
    - ``group_size``: elements per int8 scale chunk.
    - ``bucket_bytes``: byte budget per reduction bucket; each bucket is an
      independent collective that overlaps remaining backward compute
      (``runtime/zero/reduce.py``).
    - ``onebit_carrier``: wire carrier for the 1-bit optimizer family —
      ``"packed"`` (uint8 bitfield all-gather, the 32x DCN cut) or
      ``"dense"`` (f32 psum of sign x scale, the semantics baseline).
    """

    enabled: bool = False
    dtype: str = "int8"
    group_size: int = 1024
    bucket_bytes: int = 16 * 1024 * 1024
    onebit_carrier: str = "packed"

    @model_validator(mode="after")
    def _check(self):
        if self.dtype not in ("none", "int8", "1bit"):
            raise ValueError(
                f"comm_quantization.dtype must be one of none/int8/1bit, "
                f"got {self.dtype!r}")
        if self.onebit_carrier not in ("packed", "dense"):
            raise ValueError(
                f"comm_quantization.onebit_carrier must be packed or dense, "
                f"got {self.onebit_carrier!r}")
        if self.group_size <= 0 or self.bucket_bytes <= 0:
            raise ValueError(
                "comm_quantization.group_size and bucket_bytes must be "
                "positive")
        return self


class PipelineConfig(DeepSpeedConfigModel):
    """``pipeline`` section: which instruction schedule the pipeline
    engine compiles (``runtime/pipe/schedule.py``).

    - ``schedule``: ``"1f1b"`` (default — the existing schedule, byte-
      identical HLO when this section is absent), ``"interleaved"``
      (``virtual_stages`` round-robin layer chunks per physical stage,
      bubble shrinks toward ``(P-1)/(Mv+P-1)`` for ``v``x activation
      buffers), or ``"zero_bubble"`` (ZB-H1 split backward — the
      instruction stream models ``BackwardInput``/``BackwardWeight``;
      the compiled program is unchanged because XLA's scan transpose
      already owns the backward ordering, so losses stay bit-identical
      to 1F1B).
    - ``virtual_stages``: chunks per physical stage; only meaningful
      with ``schedule: interleaved``; layers must divide stages *
      virtual_stages.
    """

    schedule: str = "1f1b"
    virtual_stages: int = 1

    @model_validator(mode="after")
    def _check(self):
        if self.schedule not in ("1f1b", "interleaved", "zero_bubble"):
            raise ValueError(
                "pipeline.schedule must be one of 1f1b/interleaved/"
                f"zero_bubble, got {self.schedule!r}")
        if self.virtual_stages < 1:
            raise ValueError("pipeline.virtual_stages must be >= 1")
        if self.virtual_stages > 1 and self.schedule != "interleaved":
            raise ValueError(
                "pipeline.virtual_stages > 1 requires "
                "pipeline.schedule == 'interleaved'")
        return self


class TelemetryTraceConfig(DeepSpeedConfigModel):
    """``telemetry.trace``: capture a ``jax.profiler`` XPlane trace for
    exactly ``num_steps`` optimizer steps starting once ``start_step``
    steps have completed (``num_steps == 0`` disables the window)."""

    start_step: int = 0
    num_steps: int = 0
    dir: str = "./telemetry/trace"

    @model_validator(mode="after")
    def _check(self):
        if self.start_step < 0 or self.num_steps < 0:
            raise ValueError("telemetry.trace.start_step/num_steps must be "
                             ">= 0")
        return self


class TelemetryTracingConfig(DeepSpeedConfigModel):
    """``telemetry.tracing``: span-based causal tracing
    (``telemetry/tracing.py``) — serving request traces and training
    step-phase traces as ``span`` events on the stream, plus a per-step
    exposed-comm fraction. Off by default; enabling it changes host-side
    bookkeeping only (the compiled step/decode HLO stays byte-identical,
    pinned in ``tests/unit/test_tracing.py``)."""

    enabled: bool = False
    # per-step exposed-comm accounting: profiled from a closed
    # jax.profiler window where an XPlane parser exists, otherwise a
    # zero-overlap static estimate from the compiled step's cost model
    # (labeled as such). The two rates below are the estimate's
    # denominators; 0 = auto (device-kind defaults).
    exposed_comm: bool = True
    ici_gbps: float = 90.0
    peak_tflops: float = 0.0
    # per-mesh-axis link-rate overrides (GB/s), e.g. {"data": 25.0} to
    # price a DCN data axis below the ICI default; axes not listed fall
    # back to ici_gbps, so {} is numerically the existing single-rate
    # estimate
    axis_gbps: Dict[str, float] = Field(default_factory=dict)

    @model_validator(mode="after")
    def _check(self):
        if self.ici_gbps < 0 or self.peak_tflops < 0:
            raise ValueError("telemetry.tracing.ici_gbps/peak_tflops must "
                             "be >= 0")
        for axis, rate in self.axis_gbps.items():
            if rate <= 0:
                raise ValueError(
                    f"telemetry.tracing.axis_gbps[{axis!r}] must be > 0")
        return self


class TelemetryFlightRecorderConfig(DeepSpeedConfigModel):
    """``telemetry.flight_recorder``: a bounded in-memory ring of recent
    telemetry events (spans included) + metric-registry snapshots,
    continuously armed while telemetry is on and dumped atomically to
    ``<dump_dir>/flightrec-<ts>/`` on fault events, breaker trips,
    SIGTERM, or an explicit call — the "what was happening in the 30 s
    before the watchdog killed us" artifact. Off by default; enabling
    it changes host-side bookkeeping only (the compiled step/decode HLO
    stays byte-identical, pinned in tests/unit/test_metrics_plane.py).
    """

    enabled: bool = False
    events: int = 512          # event-ring capacity (spans ride it too)
    snapshots: int = 64        # metric-snapshot ring (0 disables)
    dump_dir: Optional[str] = None   # default: <telemetry.dir>
    max_dumps: int = 4         # per-process dump budget (fault storms
    #                            must not fill the disk)
    on_sigterm: bool = True    # chain a SIGTERM handler (preemption dump)

    @model_validator(mode="after")
    def _check(self):
        if self.events <= 0 or self.snapshots < 0 or self.max_dumps < 1:
            raise ValueError(
                "telemetry.flight_recorder needs events > 0, "
                "snapshots >= 0 and max_dumps >= 1")
        return self


class TelemetryConfig(DeepSpeedConfigModel):
    """``telemetry`` section (TPU-native): the unified observability event
    stream (``deepspeed_tpu/telemetry/``). Four collectors:

    - ``compile_watchdog``: per-jitted-function compile wall time and
      retrace count, with loud warnings on recompile storms after
      ``warmup_steps`` (``recompile_warn_after`` recompiles trip it).
    - ``hlo_cost``: once per compile, FLOPs / per-collective wire bytes /
      executable memory analysis from the compiled step program.
    - ``memory``: device memory stats sampled every ``sample_every`` step
      boundaries, passively (no added host syncs).
    - ``trace``: config-driven ``jax.profiler`` trace window.

    Events land in a rank-0-gated JSON-lines sink at
    ``<dir>/telemetry.jsonl`` (``jsonl: false`` keeps collectors live for
    the monitor bridge only) — render it with
    ``python tools/telemetry_report.py <path>``.
    """

    enabled: bool = False
    dir: str = "./telemetry"
    jsonl: bool = True
    # size-bounded sink: rotate the live telemetry.jsonl once it reaches
    # rotate_bytes (0 = never), keeping the last rotate_keep rotated
    # segments (<path>.1 newest .. <path>.K oldest)
    rotate_bytes: int = 0
    rotate_keep: int = 4
    compile_watchdog: bool = True
    hlo_cost: bool = True
    memory: bool = True
    sample_every: int = 1
    warmup_steps: int = 1
    recompile_warn_after: int = 1
    # live metrics plane (telemetry/registry.py + prom.py): a labeled
    # Counter/Gauge/Histogram registry with OpenMetrics/Prometheus text
    # exposition. metrics_port arms the registry AND serves it from a
    # stdlib http.server endpoint per process (0 = ephemeral port; None
    # = no server). metrics_file arms the registry and atomically dumps
    # the exposition text there at step boundaries (the scrape-less
    # path). Both absent (default): the registry is the inert
    # NULL_REGISTRY and nothing changes anywhere.
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    metrics_file: Optional[str] = None
    trace: TelemetryTraceConfig = Field(default_factory=TelemetryTraceConfig)
    tracing: TelemetryTracingConfig = Field(
        default_factory=TelemetryTracingConfig)
    flight_recorder: TelemetryFlightRecorderConfig = Field(
        default_factory=TelemetryFlightRecorderConfig)

    @model_validator(mode="after")
    def _check(self):
        if self.sample_every <= 0:
            raise ValueError("telemetry.sample_every must be positive")
        if self.warmup_steps < 0 or self.recompile_warn_after < 1:
            raise ValueError("telemetry.warmup_steps must be >= 0 and "
                             "recompile_warn_after >= 1")
        if self.rotate_bytes < 0 or self.rotate_keep < 1:
            raise ValueError("telemetry.rotate_bytes must be >= 0 and "
                             "rotate_keep >= 1")
        if self.metrics_port is not None and not (
                0 <= self.metrics_port <= 65535):
            raise ValueError("telemetry.metrics_port must be a valid "
                             "port (0 binds an ephemeral one) or absent")
        return self


class TuningConfig(DeepSpeedConfigModel):
    """``tuning`` section (TPU-native): consume a measured tuned-config
    artifact (``autotuning/artifact.py``) at engine build.

    - ``artifact``: path to ``tuned.json`` (default:
      ``<results_dir>/tuned.json``) — written by the live autotuner
      (``python -m deepspeed_tpu.autotuning --live`` or
      :class:`~deepspeed_tpu.autotuning.measure.LiveTuner`).
    - Precedence is explicit-user-key > artifact > default: a key this
      config file sets is never overridden by the artifact.
    - The artifact is fingerprint-pinned: consuming it on a different
      topology raises a structured
      :class:`~deepspeed_tpu.autotuning.artifact.TunedArtifactError`
      listing saved-vs-current fields.

    With the block absent nothing changes anywhere: no artifact is read,
    no kernel default is overridden, and the compiled step HLO is
    byte-identical (zero-overhead contract, pinned in
    ``tests/unit/test_live_tuning.py``).
    """

    enabled: bool = False
    artifact: Optional[str] = None
    results_dir: str = "autotuning_results"


class AOTConfig(DeepSpeedConfigModel):
    """``aot`` section (TPU-native): ship the engine's steady-state
    compiled executables with every checkpoint (``deepspeed_tpu/aot``)
    and pre-populate dispatch on resume, so a same-topology restart
    reaches its first step without recompiling the world.

    Requires ``telemetry.enabled`` (with the compile watchdog or HLO
    cost collector on): the telemetry ``WatchedFunction`` layer is what
    holds the compiled executables. Enabling ``aot`` without it is a
    config error, not a silent no-op.

    - ``fail_on_mismatch``: a shipped bundle whose identity (jaxlib
      version, topology fingerprint, tuned-config hash) mismatches the
      live runtime raises instead of warning + compiling normally.
      (Not named ``strict``: the base config model's constructor
      consumes that kwarg for auto-value handling.)

    Environments where executable deserialization is known-crashy
    (jaxlib < 0.5 multi-device CPU — ``utils/compat.
    aot_serialization_safe``) skip capture/restore with a loud
    ``aot``/``disabled`` telemetry event and compile normally.
    """

    enabled: bool = False
    fail_on_mismatch: bool = False


class ResilienceCheckpointConfig(DeepSpeedConfigModel):
    """``resilience.checkpoint``: integrity manifests + fallback chain +
    IO retry + retention (``runtime/resilience/integrity.py``).

    - ``integrity``: write a per-file sha256 manifest as the ``commit()``
      step and record the tag verified-good.
    - ``verify_on_load``: re-check the manifest before any bytes
      deserialize; a mismatch raises ``CheckpointCorruptionError`` and
      (on a ``latest`` resume) falls back down the verified-good chain.
    - ``fallback``: enable the resume fallback chain
      (``latest`` → previous verified-good tags, newest first).
    - ``retries`` / ``retry_backoff_secs``: transient save/load IO errors
      retry with exponential backoff (``backoff * 2**attempt``).
    - ``keep_last_n``: retention over *verified* tags; ``0`` keeps all.
      The newest verified-good tag and the elastic agent's ``preempt``
      tag are never deleted.
    - ``rollback_dir``: pins where ``sentinel.policy: rollback`` restores
      from (default: the last ``save_checkpoint`` directory).
    """

    integrity: bool = True
    verify_on_load: bool = True
    fallback: bool = True
    retries: int = 3
    retry_backoff_secs: float = 0.2
    keep_last_n: int = 0
    rollback_dir: Optional[str] = None

    @model_validator(mode="after")
    def _check(self):
        if self.retries < 0 or self.retry_backoff_secs < 0:
            raise ValueError("resilience.checkpoint.retries and "
                             "retry_backoff_secs must be >= 0")
        if self.keep_last_n < 0:
            raise ValueError("resilience.checkpoint.keep_last_n must be "
                             ">= 0 (0 keeps everything)")
        return self


class ResilienceSentinelConfig(DeepSpeedConfigModel):
    """``resilience.sentinel``: NaN/Inf + loss-spike detection at every
    optimizer boundary (``runtime/resilience/sentinel.py``) — the bf16
    protection the fp16 overflow path never covered.

    - ``policy``: ``warn`` (log + fault event) | ``skip`` (compile the
      fp16-style grads NaN/Inf check into the step: a bad step is
      skipped exactly like an fp16 overflow) | ``abort`` (raise out of
      ``engine.step()``) | ``rollback`` (restore the last verified-good
      checkpoint in place).
    - ``loss_spike_factor``: trip when loss > factor x trailing-window
      mean (``0`` disables spike detection; nonfinite always trips).
    - ``loss_window`` / ``min_history``: trailing window size and the
      minimum samples before spike detection arms.
    - ``sync_lag``: boundaries to hold each loss before the host reads it
      (``0`` checks immediately at the cost of run-ahead).
    - ``max_rollbacks``: rollbacks tolerated before escalating to abort
      (``0`` = unlimited).
    """

    enabled: bool = True
    policy: str = "warn"
    loss_spike_factor: float = 0.0
    loss_window: int = 32
    min_history: int = 4
    sync_lag: int = 1
    max_rollbacks: int = 3

    @model_validator(mode="after")
    def _check(self):
        if self.policy not in ("warn", "skip", "abort", "rollback"):
            raise ValueError(
                "resilience.sentinel.policy must be one of warn/skip/"
                f"abort/rollback, got {self.policy!r}")
        if self.loss_window <= 0 or self.min_history < 1:
            raise ValueError("resilience.sentinel.loss_window must be > 0 "
                             "and min_history >= 1")
        if self.sync_lag < 0 or self.loss_spike_factor < 0 \
                or self.max_rollbacks < 0:
            raise ValueError("resilience.sentinel.sync_lag, "
                             "loss_spike_factor and max_rollbacks must be "
                             ">= 0")
        return self


class ResilienceWatchdogConfig(DeepSpeedConfigModel):
    """``resilience.watchdog``: background stall detector
    (``runtime/resilience/watchdog.py``). Arms at the first completed
    optimizer step (initial compiles can never trip it); on
    ``timeout_secs`` without step progress it dumps every Python thread's
    stack + the telemetry event tail to ``dump_dir`` and (``abort``)
    SIGTERMs then hard-exits with ``exit_code`` so the supervisor
    restarts the job."""

    enabled: bool = True
    timeout_secs: float = 600.0
    poll_secs: float = 0.0  # 0 = auto (timeout/4, capped at 10s)
    dump_dir: str = "./resilience"
    abort: bool = True
    exit_code: int = 43

    @model_validator(mode="after")
    def _check(self):
        if self.timeout_secs <= 0 or self.poll_secs < 0:
            raise ValueError("resilience.watchdog.timeout_secs must be > 0 "
                             "and poll_secs >= 0")
        return self


class ResilienceConfig(DeepSpeedConfigModel):
    """``resilience`` section (TPU-native): the fault-tolerance layer
    (``deepspeed_tpu/runtime/resilience/``). Off by default; with the
    block absent or disabled the compiled train step is byte-identical
    to a resilience-free build (same zero-overhead contract as
    ``telemetry``)."""

    enabled: bool = False
    checkpoint: ResilienceCheckpointConfig = Field(
        default_factory=ResilienceCheckpointConfig)
    sentinel: ResilienceSentinelConfig = Field(
        default_factory=ResilienceSentinelConfig)
    watchdog: ResilienceWatchdogConfig = Field(
        default_factory=ResilienceWatchdogConfig)


def _resolve_batch_triangle(train_batch, micro_batch, gas, dp_world_size):
    """Resolve/validate train_batch = micro_batch * gas * dp_world.

    Mirrors reference ``DeepSpeedConfig._configure_train_batch_size``
    (``runtime/config.py:918-989``): any two given determine the third; one
    given fills the others with sensible defaults; none given is an error.
    """
    tb, mb, g = train_batch, micro_batch, gas
    if tb is not None and mb is not None and g is not None:
        if tb != mb * g * dp_world_size:
            raise DeepSpeedConfigError(
                f"Check batch related parameters. train_batch_size is not equal to "
                f"micro_batch_per_gpu * gradient_acc_step * world_size "
                f"{tb} != {mb} * {g} * {dp_world_size}"
            )
    elif tb is not None and mb is not None:
        g, rem = divmod(tb, mb * dp_world_size)
        if rem != 0:
            raise DeepSpeedConfigError(
                f"train_batch_size {tb} not divisible by micro_batch {mb} * world size {dp_world_size}"
            )
    elif tb is not None and g is not None:
        mb, rem = divmod(tb, g * dp_world_size)
        if rem != 0:
            raise DeepSpeedConfigError(
                f"train_batch_size {tb} not divisible by gas {g} * world size {dp_world_size}"
            )
    elif mb is not None and g is not None:
        tb = mb * g * dp_world_size
    elif tb is not None:
        g = 1
        mb, rem = divmod(tb, dp_world_size)
        if rem != 0:
            raise DeepSpeedConfigError(f"train_batch_size {tb} not divisible by world size {dp_world_size}")
    elif mb is not None:
        g = 1
        tb = mb * dp_world_size
    else:
        raise DeepSpeedConfigError(
            "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided"
        )
    for name, v in (("train_batch_size", tb), ("train_micro_batch_size_per_gpu", mb),
                    ("gradient_accumulation_steps", g)):
        if v <= 0:
            raise DeepSpeedConfigError(f"{name} must be positive, got {v}")
    return tb, mb, g


class DeepSpeedConfig:
    """Parsed master config.

    ``config`` may be a dict, a path to a JSON file, or None. ``world_size``
    here means the *data-parallel* world size used in batch arithmetic
    (reference passes ``mpu.get_data_parallel_world_size()``).
    """

    def __init__(self, config: Any, mpu=None, world_size: Optional[int] = None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"DeepSpeed config path does not exist: {config}")
            with open(config) as f:
                self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        elif config is None:
            self._param_dict = {}
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path to a JSON file or a dict, got: {type(config)}")

        d = self._param_dict
        # --- sub-models ---
        self.fp16 = FP16Config(**d.get(C.FP16, {}))
        self.bf16 = BF16Config(**d.get(C.BF16, d.get("bfloat16", {})))
        self.amp = AMPConfig(**d.get(C.AMP, {}))
        self.zero_config = DeepSpeedZeroConfig(**d.get(C.ZERO_OPTIMIZATION, {}))
        mesh_raw = d.get(C.MESH, {})
        self.activation_checkpointing_config = ActivationCheckpointingConfig(
            **d.get("activation_checkpointing", {}))
        # only an explicit enabled/policy key drives model reconfiguration in
        # the engine; parity-boilerplate sections carrying only the
        # reference's fields (partition_activations etc.) stay parse-only, so
        # existing configs don't silently flip remat on
        _ac = d.get("activation_checkpointing", {})
        self.activation_checkpointing_explicit = (
            "enabled" in _ac or "policy" in _ac)
        self.comms_config = CommsLoggerConfig(**d.get("comms_logger", {}))
        self.flops_profiler_config = FlopsProfilerConfig(**d.get("flops_profiler", {}))
        self.monitor_config = MonitorConfig(
            tensorboard=d.get("tensorboard", {}),
            wandb=d.get("wandb", {}),
            csv_monitor=d.get("csv_monitor", {}),
        )
        self.checkpoint_config = CheckpointConfig(**d.get(C.CHECKPOINT, {}))
        self.nebula_config = NebulaConfig(**d.get("nebula", {}))
        self.data_types_config = DataTypesConfig(**d.get(C.DATA_TYPES, {}))
        # --- live tuned-config artifact (``tuning`` block) ---
        # loaded BEFORE the sections it feeds parse, so precedence is
        # uniform: a key the user wrote in this config wins, a key only
        # the artifact carries fills in, everything else defaults
        self.tuning_config = TuningConfig(**d.get("tuning", {}))
        self.tuned_artifact = None
        self.tuned_ops: Dict[str, Any] = {}
        cq_raw = d.get("comm_quantization", {})
        if self.tuning_config.enabled:
            from deepspeed_tpu.autotuning.artifact import (apply_section,
                                                           load_for_config,
                                                           ops_choices)

            try:
                # shared consumption entry point (inference uses the
                # same one): missing-artifact guidance + the loud,
                # structured fingerprint gate live in exactly one place
                self.tuned_artifact = load_for_config(
                    {"artifact": self.tuning_config.artifact,
                     "results_dir": self.tuning_config.results_dir})
            except FileNotFoundError as e:
                raise DeepSpeedConfigError(str(e))
            # the comm.tier axis owns the section's `enabled` decision
            # (its grid measured the machinery-off default too, so
            # enabling here is a MEASURED choice — see
            # artifact._expand_section_target); a bucket-bytes-only
            # artifact fills bucket_bytes without flipping the section
            # on, and an explicit user `enabled` key always wins
            cq_raw = apply_section(cq_raw, self.tuned_artifact,
                                   "comm_quantization")
            # measured mesh factorization (the autotuner's mesh.shape
            # axis): the (data, fsdp, tp) triple was measured as a UNIT,
            # so it applies only when the user pinned no axis at all —
            # mixing a user-pinned axis with two tuned ones would run a
            # factorization nobody measured
            if not any(k in mesh_raw for k in
                       ("data", "fsdp", "tp", "model", "pipe", "expert",
                        "seq")):
                mesh_raw = apply_section(mesh_raw, self.tuned_artifact,
                                         "mesh")
            # Pallas tile choices: the engine installs these into the
            # kernel-default registry at build (and removes them at
            # destroy) — kernels resolve explicit arg > tuned > default
            self.tuned_ops = ops_choices(self.tuned_artifact)
        self.comm_quantization = CommQuantizationConfig(**cq_raw)
        self.mesh = MeshConfig(**mesh_raw)
        self.pipeline_config = PipelineConfig(**d.get("pipeline", {}))
        self.telemetry_config = TelemetryConfig(**d.get("telemetry", {}))
        self.resilience_config = ResilienceConfig(**d.get("resilience", {}))
        self.aot_config = AOTConfig(**d.get("aot", {}))
        if self.aot_config.enabled and not (
                self.telemetry_config.enabled
                and (self.telemetry_config.compile_watchdog
                     or self.telemetry_config.hlo_cost)):
            raise DeepSpeedConfigError(
                "aot.enabled requires telemetry.enabled (with the "
                "compile_watchdog or hlo_cost collector on): the "
                "telemetry WatchedFunction layer is what holds the "
                "compiled executables the AOT bundle ships")

        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")

        # --- scalars ---
        self.optimizer_name = None
        self.optimizer_params = None
        self.optimizer_legacy_fusion = False
        opt = d.get(C.OPTIMIZER)
        if opt:
            self.optimizer_name = opt.get(C.TYPE)
            if self.optimizer_name:
                self.optimizer_name = self.optimizer_name.lower()
            self.optimizer_params = opt.get(C.OPTIMIZER_PARAMS, {})
            self.optimizer_legacy_fusion = opt.get(C.LEGACY_FUSION, False)
        sched = d.get(C.SCHEDULER)
        self.scheduler_name = sched.get(C.TYPE) if sched else None
        self.scheduler_params = sched.get(C.SCHEDULER_PARAMS, {}) if sched else None

        self.zero_allow_untested_optimizer = d.get(
            C.ZERO_ALLOW_UNTESTED_OPTIMIZER, C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)
        self.steps_per_print = d.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = d.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.disable_allgather = d.get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)
        self.gradient_predivide_factor = d.get(
            C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.prescale_gradients = d.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_clipping = d.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)
        self.communication_data_type = d.get(
            C.COMMUNICATION_DATA_TYPE, C.COMMUNICATION_DATA_TYPE_DEFAULT)
        self.sparse_gradients_enabled = d.get(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.wall_clock_breakdown = d.get(C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = d.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)
        self.dataloader_drop_last = d.get(C.DATALOADER_DROP_LAST, C.DATALOADER_DROP_LAST_DEFAULT)
        # fuse forward+backward+optimizer into ONE compiled program when
        # gradient_accumulation_steps == 1 (no grad-accumulation buffer
        # round-trip, one dispatch per step). Requires the canonical
        # forward→backward→step call order per batch — hence opt-in.
        self.fused_step = d.get("fused_step", False)

        self.pld_enabled = d.get(C.PLD, {}).get(C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT)
        self.pld_params = d.get(C.PLD, {}) if self.pld_enabled else False
        self.curriculum_enabled_legacy = d.get(C.CURRICULUM_LEARNING, {}).get(
            C.CURRICULUM_ENABLED, C.CURRICULUM_ENABLED_DEFAULT)
        self.curriculum_params_legacy = d.get(C.CURRICULUM_LEARNING, {})
        self.data_efficiency_config = d.get(C.DATA_EFFICIENCY, {})

        self.eigenvalue_enabled = d.get(C.EIGENVALUE, {}).get(
            C.EIGENVALUE_ENABLED, C.EIGENVALUE_ENABLED_DEFAULT)
        self.eigenvalue_params = d.get(C.EIGENVALUE, {})
        self.sparse_attention = d.get(C.SPARSE_ATTENTION)
        self.autotuning_config = d.get(C.AUTOTUNING, {})
        # TP policy selection (reference: injection_policy / replace_policy);
        # TP *degree* comes from mesh.tp
        self.tensor_parallel_config = d.get("tensor_parallel", {})
        self.elasticity_config = d.get(C.ELASTICITY, {})
        self.compression_config = d.get("compression_training", {})
        self.aio_config = d.get("aio", {})

        # --- batch triangle ---
        if world_size is None:
            if mpu is not None:
                world_size = mpu.get_data_parallel_world_size()
            else:
                # Data-parallel world = devices not consumed by
                # tp/pipe/seq/fsdp (fsdp shards weights, not the batch —
                # SpecLayout.batch_axes). (The expert axis folds into data
                # for batch purposes: ep <= dp, as in the reference's
                # expert+data group factory.)
                non_data = (self.mesh.tp * self.mesh.pipe * self.mesh.seq
                            * self.mesh.fsdp)
                world_size = int(os.environ.get("WORLD_SIZE", 1)) // max(1, non_data)
                world_size = max(1, world_size)
        self.world_size = world_size
        tb = d.get(C.TRAIN_BATCH_SIZE)
        mb = d.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        gas = d.get(C.GRADIENT_ACCUMULATION_STEPS)
        tb = None if tb == "auto" else tb
        mb = None if mb == "auto" else mb
        gas = None if gas == "auto" else gas
        (self.train_batch_size, self.train_micro_batch_size_per_gpu,
         self.gradient_accumulation_steps) = _resolve_batch_triangle(tb, mb, gas, world_size)

        # checkpoint knobs (flattened accessors used by the engine)
        self.checkpoint_tag_validation_enabled = self.checkpoint_config.tag_validation != "Ignore"
        self.checkpoint_tag_validation_fail = self.checkpoint_config.tag_validation == "Fail"
        self.load_universal_checkpoint = self.checkpoint_config.load_universal
        self.use_node_local_storage = self.checkpoint_config.use_node_local_storage

    # ------------------------------------------------------------------
    @property
    def tuned_artifact_hash(self) -> str:
        """Identity of the tuned config this engine was built under —
        one component of the AOT bundle cache key ("none" untuned)."""
        from deepspeed_tpu.autotuning.artifact import artifact_hash

        return artifact_hash(self.tuned_artifact)

    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def precision_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def print_user_config(self):
        logger.info("  json = {}".format(
            json.dumps(self._param_dict, sort_keys=True, indent=4, default=str)))

    def print(self, name):
        logger.info(f"{name}:")
        for key in sorted(self.__dict__):
            if key != "_param_dict":
                logger.info(f"  {key} {self.__dict__[key]}")
        self.print_user_config()
