"""State-dict factory: HF/Megatron checkpoint ingestion.

Capability parity with the reference ``runtime/state_dict_factory.py``
(``SDLoaderFactory``:20, ``MegatronSDLoader``:214): load a foreign
checkpoint (HuggingFace torch/safetensors, Megatron shards, raw npz),
normalize each architecture's weight naming + QKV packing into a canonical
per-layer layout, and materialize parameters for this framework's models.
The reference's TP-degree reshaping (QKV merge/split across mp ranks) is
kept as explicit utilities; actual placement-on-mesh happens downstream via
sharding specs (module_inject/policies.py), not by physically slicing here.

Canonical per-layer layout (all arrays ``[in, out]`` like flax Dense):
    ln_1.{scale,bias}         pre-attention layernorm
    c_attn.{kernel,bias}      fused QKV  [C, 3C] — Q|K|V concatenated
    c_proj.{kernel,bias}      attention output  [C, C]
    ln_2.{scale,bias}         pre-MLP layernorm
    c_fc.{kernel,bias}        MLP up  [C, hidden]
    mlp_c_proj.{kernel,bias}  MLP down  [hidden, C]
plus model-level ``wte``/``wpe``/``ln_f``.
"""

import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger

# ----------------------------------------------------------------------
# QKV packing utilities (reference MegatronSDLoader merge/split,
# state_dict_factory.py:214,282,328)


def merge_qkv(q: np.ndarray, k: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Separate Q/K/V projections ([C, C] each, flax orientation) → fused
    [C, 3C] (the packing GPT-2's ``c_attn`` uses; OPT/LLaMA store them
    separately)."""
    return np.concatenate([q, k, v], axis=-1)


def split_qkv(fused: np.ndarray, out_axis: int = -1):
    """Fused [..., 3C] → (q, k, v). Inverse of :func:`merge_qkv`."""
    return tuple(np.split(fused, 3, axis=out_axis))


def deinterleave_bloom_qkv(w: np.ndarray, n_head: int) -> np.ndarray:
    """BLOOM packs QKV interleaved PER HEAD: the out dim is
    [h0q, h0k, h0v, h1q, h1k, h1v, ...]; the canonical layout wants
    [all-q | all-k | all-v] (reference handles this reordering in its BLOOM
    injection container). Accepts [..., 3C] (flax orientation, out last)."""
    *lead, out = w.shape
    c = out // 3
    hd = c // n_head
    w = w.reshape(*lead, n_head, 3, hd)
    q, k, v = w[..., 0, :], w[..., 1, :], w[..., 2, :]
    return np.concatenate(
        [x.reshape(*lead, c) for x in (q, k, v)], axis=-1)


def shard_qkv_for_tp(fused: np.ndarray, tp_size: int, rank: int,
                     out_axis: int = -1) -> np.ndarray:
    """TP reshaping of a fused QKV weight: slice EACH of Q, K, V (not the
    raw concat) so every rank holds heads for all three (reference
    ``qkv_split`` merge logic, state_dict_factory.py:328)."""
    qkv = np.split(fused, 3, axis=out_axis)
    shards = [np.split(x, tp_size, axis=out_axis)[rank] for x in qkv]
    return np.concatenate(shards, axis=out_axis)


def merge_qkv_tp_shards(shards, out_axis: int = -1) -> np.ndarray:
    """Inverse of :func:`shard_qkv_for_tp`: per-rank fused shards → full
    fused weight (reference ``merge_query_key_value``,
    state_dict_factory.py:282)."""
    per_rank = [np.split(s, 3, axis=out_axis) for s in shards]
    merged = [np.concatenate([r[i] for r in per_rank], axis=out_axis)
              for i in range(3)]
    return np.concatenate(merged, axis=out_axis)


# ----------------------------------------------------------------------
# Raw checkpoint loading


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    # torch tensor (transformers checkpoints) without importing torch here
    if hasattr(t, "detach"):
        t = t.detach()
        if hasattr(t, "cpu"):
            t = t.cpu()
        if str(getattr(t, "dtype", "")) == "torch.bfloat16":
            t = t.float()
        return t.numpy()
    return np.asarray(t)


class SDLoaderFactory:
    """Entry point (reference ``SDLoaderFactory.get_sd_loader_json``:20)."""

    @staticmethod
    def load(src) -> Dict[str, np.ndarray]:
        """Name→numpy mapping from: a dict (torch/numpy state_dict), an
        ``.npz``/``.bin``/``.pt``/``.safetensors`` file, or an HF model
        directory containing one of those."""
        if isinstance(src, dict):
            return {k: _to_numpy(v) for k, v in src.items()}
        path = str(src)
        if os.path.isdir(path):
            # HF sharded checkpoints (how every large model ships):
            # weight_map index names the shard file per tensor
            for idx_name in ("model.safetensors.index.json",
                             "pytorch_model.bin.index.json"):
                idx_path = os.path.join(path, idx_name)
                if os.path.exists(idx_path):
                    import json

                    with open(idx_path) as f:
                        weight_map = json.load(f)["weight_map"]
                    out = {}
                    for shard in sorted(set(weight_map.values())):
                        out.update(SDLoaderFactory.load(
                            os.path.join(path, shard)))
                    return out
            for name in ("model.safetensors", "pytorch_model.bin",
                         "weights.npz"):
                cand = os.path.join(path, name)
                if os.path.exists(cand):
                    path = cand
                    break
            else:
                raise FileNotFoundError(
                    f"no checkpoint file found under {path!r}")
        if path.endswith(".npz"):
            with np.load(path) as z:
                # engine.save_16bit_model's no-safetensors fallback stores
                # bf16 tensors as uint16 views plus this sidecar key
                bf16 = set(np.atleast_1d(z["__bf16_keys__"]).tolist()) \
                    if "__bf16_keys__" in z.files else set()
                import jax.numpy as jnp
                return {k: z[k].view(jnp.bfloat16) if k in bf16 else z[k]
                        for k in z.files if k != "__bf16_keys__"}
        if path.endswith(".safetensors"):
            from safetensors.numpy import load_file

            return load_file(path)
        # torch pickle (pytorch_model.bin / *.pt)
        import torch

        sd = torch.load(path, map_location="cpu", weights_only=True)
        if isinstance(sd, dict) and "state_dict" in sd:
            sd = sd["state_dict"]
        return {k: _to_numpy(v) for k, v in sd.items()}


# ----------------------------------------------------------------------
# Per-architecture weight maps (reference replace_policy.py arch classes +
# MegatronSDLoader normalization)


class HFWeightMap:
    """Normalizes one HF architecture's state dict into the canonical
    layout. Subclasses define regexes for the per-layer names and a
    ``convert_layer`` that fixes orientation/packing."""

    arch = "base"
    layer_re = re.compile(r"^transformer\.h\.(\d+)\.(.+)$")
    # canonical key -> HF suffix within a layer
    layer_map: Dict[str, str] = {}
    top_map: Dict[str, str] = {}
    # HF Linear stores [out, in] and needs a transpose to flax [in, out];
    # GPT-2's Conv1D already stores [in, out]
    transpose_linear = True

    def n_layers(self, sd) -> int:
        ids = [int(m.group(1)) for k in sd
               if (m := self.layer_re.match(k))]
        return max(ids) + 1 if ids else 0

    @staticmethod
    def lookup(sd, key):
        """Fetch ``key`` tolerating the model-prefix variants hub
        checkpoints ship: ``GPT2LMHeadModel`` saves ``transformer.*`` /
        ``BloomForCausalLM`` saves ``transformer.*``, but the bare
        ``GPT2Model``/``BloomModel`` checkpoints omit the prefix and OPT
        ships both ``model.decoder.*`` and ``decoder.*`` forms."""
        if key in sd:
            return sd[key]
        for prefix in ("transformer.", "model."):
            if key.startswith(prefix) and key[len(prefix):] in sd:
                return sd[key[len(prefix):]]
        return None

    def layer_weights(self, sd, i: int) -> Dict[str, np.ndarray]:
        out = {}
        for canon, suffix in self.layer_map.items():
            w = self.lookup(sd, self.layer_key(i, suffix))
            if w is not None:
                out[canon] = self.convert(canon, w)
        return out

    def layer_key(self, i: int, suffix: str) -> str:
        raise NotImplementedError

    def convert(self, canon: str, w: np.ndarray) -> np.ndarray:
        if canon.endswith(".kernel") and self.transpose_linear and w.ndim == 2:
            return np.ascontiguousarray(w.T)
        return w

    def top_weights(self, sd) -> Dict[str, np.ndarray]:
        out = {}
        for canon, key in self.top_map.items():
            w = self.lookup(sd, key)
            if w is not None:
                out[canon] = self.convert(canon, w)
        return out


class GPT2WeightMap(HFWeightMap):
    """HF ``GPT2LMHeadModel`` (Conv1D weights are already [in, out])."""

    arch = "gpt2"
    transpose_linear = False
    layer_re = re.compile(r"^(?:transformer\.)?h\.(\d+)\.(.+)$")
    layer_map = {
        "ln_1.scale": "ln_1.weight", "ln_1.bias": "ln_1.bias",
        "c_attn.kernel": "attn.c_attn.weight", "c_attn.bias": "attn.c_attn.bias",
        "c_proj.kernel": "attn.c_proj.weight", "c_proj.bias": "attn.c_proj.bias",
        "ln_2.scale": "ln_2.weight", "ln_2.bias": "ln_2.bias",
        "c_fc.kernel": "mlp.c_fc.weight", "c_fc.bias": "mlp.c_fc.bias",
        "mlp_c_proj.kernel": "mlp.c_proj.weight",
        "mlp_c_proj.bias": "mlp.c_proj.bias",
    }
    top_map = {
        "wte": "transformer.wte.weight", "wpe": "transformer.wpe.weight",
        "ln_f.scale": "transformer.ln_f.weight",
        "ln_f.bias": "transformer.ln_f.bias",
    }

    def layer_key(self, i, suffix):
        return f"transformer.h.{i}.{suffix}"


class OPTWeightMap(HFWeightMap):
    """HF ``OPTForCausalLM``: separate q/k/v linears ([out, in]) are
    transposed and merged into the canonical fused c_attn."""

    arch = "opt"
    layer_re = re.compile(r"^(?:model\.)?decoder\.layers\.(\d+)\.(.+)$")
    layer_map = {
        "ln_1.scale": "self_attn_layer_norm.weight",
        "ln_1.bias": "self_attn_layer_norm.bias",
        "c_proj.kernel": "self_attn.out_proj.weight",
        "c_proj.bias": "self_attn.out_proj.bias",
        "ln_2.scale": "final_layer_norm.weight",
        "ln_2.bias": "final_layer_norm.bias",
        "c_fc.kernel": "fc1.weight", "c_fc.bias": "fc1.bias",
        "mlp_c_proj.kernel": "fc2.weight", "mlp_c_proj.bias": "fc2.bias",
    }
    top_map = {
        "wte": "model.decoder.embed_tokens.weight",
        "wpe": "model.decoder.embed_positions.weight",
        "ln_f.scale": "model.decoder.final_layer_norm.weight",
        "ln_f.bias": "model.decoder.final_layer_norm.bias",
    }

    def layer_key(self, i, suffix):
        return f"model.decoder.layers.{i}.{suffix}"

    def layer_weights(self, sd, i):
        out = super().layer_weights(sd, i)
        pre = f"model.decoder.layers.{i}.self_attn"
        ws = [self.lookup(sd, f"{pre}.{n}_proj.weight") for n in "qkv"]
        bs = [self.lookup(sd, f"{pre}.{n}_proj.bias") for n in "qkv"]
        if any(w is None for w in ws) or any(b is None for b in bs):
            return out
        qw, kw, vw = (np.ascontiguousarray(w.T) for w in ws)
        out["c_attn.kernel"] = merge_qkv(qw, kw, vw)
        out["c_attn.bias"] = np.concatenate(bs, axis=-1)
        return out


class BloomWeightMap(HFWeightMap):
    """HF ``BloomForCausalLM``: fused ``query_key_value`` is interleaved
    per head; de-interleave into the canonical Q|K|V concat. ``n_head``
    must be supplied (it is not recoverable from shapes alone)."""

    arch = "bloom"
    layer_re = re.compile(r"^(?:transformer\.)?h\.(\d+)\.(.+)$")
    layer_map = {
        "ln_1.scale": "input_layernorm.weight",
        "ln_1.bias": "input_layernorm.bias",
        "c_proj.kernel": "self_attention.dense.weight",
        "c_proj.bias": "self_attention.dense.bias",
        "ln_2.scale": "post_attention_layernorm.weight",
        "ln_2.bias": "post_attention_layernorm.bias",
        "c_fc.kernel": "mlp.dense_h_to_4h.weight",
        "c_fc.bias": "mlp.dense_h_to_4h.bias",
        "mlp_c_proj.kernel": "mlp.dense_4h_to_h.weight",
        "mlp_c_proj.bias": "mlp.dense_4h_to_h.bias",
    }
    top_map = {
        "wte": "transformer.word_embeddings.weight",
        "ln_f.scale": "transformer.ln_f.weight",
        "ln_f.bias": "transformer.ln_f.bias",
        "emb_ln.scale": "transformer.word_embeddings_layernorm.weight",
        "emb_ln.bias": "transformer.word_embeddings_layernorm.bias",
    }

    def __init__(self, n_head: int):
        self.n_head = n_head

    def layer_key(self, i, suffix):
        return f"transformer.h.{i}.{suffix}"

    def layer_weights(self, sd, i):
        out = super().layer_weights(sd, i)
        w = self.lookup(sd, self.layer_key(
            i, "self_attention.query_key_value.weight"))
        if w is not None:  # [C, 3C] after transpose, head-interleaved
            out["c_attn.kernel"] = deinterleave_bloom_qkv(
                np.ascontiguousarray(w.T), self.n_head)
        b = self.lookup(sd, self.layer_key(
            i, "self_attention.query_key_value.bias"))
        if b is not None:
            out["c_attn.bias"] = deinterleave_bloom_qkv(
                b[None], self.n_head)[0]
        return out


class LlamaWeightMap(HFWeightMap):
    """HF ``LlamaForCausalLM``: separate no-bias q/k/v/o linears, SwiGLU
    MLP, RMSNorms. Canonical keys here name the flax tree directly (the
    Llama model keeps the HF module names, models/llama.py)."""

    arch = "llama"
    layer_re = re.compile(r"^(?:model\.)?layers\.(\d+)\.(.+)$")
    layer_map = {
        "input_layernorm.scale": "input_layernorm.weight",
        "post_attention_layernorm.scale": "post_attention_layernorm.weight",
        "self_attn.q_proj.kernel": "self_attn.q_proj.weight",
        "self_attn.k_proj.kernel": "self_attn.k_proj.weight",
        "self_attn.v_proj.kernel": "self_attn.v_proj.weight",
        "self_attn.o_proj.kernel": "self_attn.o_proj.weight",
        "mlp.gate_proj.kernel": "mlp.gate_proj.weight",
        "mlp.up_proj.kernel": "mlp.up_proj.weight",
        "mlp.down_proj.kernel": "mlp.down_proj.weight",
    }
    top_map = {
        "embed_tokens": "model.embed_tokens.weight",
        "norm.scale": "model.norm.weight",
        "lm_head": "lm_head.weight",  # [V, C]: our head einsum wants [V, C]
    }

    def layer_key(self, i, suffix):
        return f"model.layers.{i}.{suffix}"

    def convert(self, canon, w):
        if canon == "lm_head" or canon == "embed_tokens":
            return w  # [V, C] both sides
        return super().convert(canon, w)


class GPTJWeightMap(HFWeightMap):
    """HF ``GPTJForCausalLM``: separate bias-free q/k/v/out linears,
    fc_in/fc_out MLP, a single per-block layernorm (parallel residual),
    and an untied lm_head with bias."""

    arch = "gptj"
    layer_re = re.compile(r"^(?:transformer\.)?h\.(\d+)\.(.+)$")
    layer_map = {
        "ln_1.scale": "ln_1.weight", "ln_1.bias": "ln_1.bias",
        "c_proj.kernel": "attn.out_proj.weight",
        "c_fc.kernel": "mlp.fc_in.weight", "c_fc.bias": "mlp.fc_in.bias",
        "mlp_c_proj.kernel": "mlp.fc_out.weight",
        "mlp_c_proj.bias": "mlp.fc_out.bias",
    }
    top_map = {
        "wte": "transformer.wte.weight",
        "ln_f.scale": "transformer.ln_f.weight",
        "ln_f.bias": "transformer.ln_f.bias",
        "lm_head": "lm_head.weight",  # [V, C]: head einsum wants [V, C]
        "lm_head_bias": "lm_head.bias",
    }

    def layer_key(self, i, suffix):
        return f"transformer.h.{i}.{suffix}"

    def convert(self, canon, w):
        if canon == "lm_head":
            return w
        return super().convert(canon, w)

    def layer_weights(self, sd, i):
        out = super().layer_weights(sd, i)
        ws = [self.lookup(sd, self.layer_key(i, f"attn.{n}_proj.weight"))
              for n in "qkv"]
        if all(w is not None for w in ws):
            qw, kw, vw = (np.ascontiguousarray(w.T) for w in ws)
            out["c_attn.kernel"] = merge_qkv(qw, kw, vw)
        return out


class GPTNeoXWeightMap(HFWeightMap):
    """HF ``GPTNeoXForCausalLM``: fused ``query_key_value`` packed per head
    (same [n_head, 3, head_dim] interleave as BLOOM), parallel residual with
    two layernorms, untied ``embed_out`` head. ``n_head`` must be supplied
    (the de-interleave depends on it)."""

    arch = "gpt-neox"
    layer_re = re.compile(r"^(?:gpt_neox\.)?layers\.(\d+)\.(.+)$")
    layer_map = {
        "ln_1.scale": "input_layernorm.weight",
        "ln_1.bias": "input_layernorm.bias",
        "ln_2.scale": "post_attention_layernorm.weight",
        "ln_2.bias": "post_attention_layernorm.bias",
        "c_proj.kernel": "attention.dense.weight",
        "c_proj.bias": "attention.dense.bias",
        "c_fc.kernel": "mlp.dense_h_to_4h.weight",
        "c_fc.bias": "mlp.dense_h_to_4h.bias",
        "mlp_c_proj.kernel": "mlp.dense_4h_to_h.weight",
        "mlp_c_proj.bias": "mlp.dense_4h_to_h.bias",
    }
    top_map = {
        "wte": "gpt_neox.embed_in.weight",
        "ln_f.scale": "gpt_neox.final_layer_norm.weight",
        "ln_f.bias": "gpt_neox.final_layer_norm.bias",
        "lm_head": "embed_out.weight",
    }

    def __init__(self, n_head: int):
        self.n_head = n_head

    @staticmethod
    def lookup(sd, key):
        if key in sd:
            return sd[key]
        if key.startswith("gpt_neox.") and key[len("gpt_neox."):] in sd:
            return sd[key[len("gpt_neox."):]]
        return None

    def layer_key(self, i, suffix):
        return f"gpt_neox.layers.{i}.{suffix}"

    def convert(self, canon, w):
        if canon == "lm_head":
            return w
        return super().convert(canon, w)

    def layer_weights(self, sd, i):
        out = super().layer_weights(sd, i)
        w = self.lookup(sd, self.layer_key(
            i, "attention.query_key_value.weight"))
        if w is not None:  # [C, 3C] after transpose, head-interleaved
            out["c_attn.kernel"] = deinterleave_bloom_qkv(
                np.ascontiguousarray(w.T), self.n_head)
        b = self.lookup(sd, self.layer_key(
            i, "attention.query_key_value.bias"))
        if b is not None:
            out["c_attn.bias"] = deinterleave_bloom_qkv(
                b[None], self.n_head)[0]
        return out


class BertWeightMap(HFWeightMap):
    """HF ``BertForMaskedLM`` → models/bert.py tree (post-LN encoder,
    tied MLM decoder + bias)."""

    arch = "bert"
    layer_re = re.compile(r"^(?:bert\.)?encoder\.layer\.(\d+)\.(.+)$")
    layer_map = {
        "attention.self.query.kernel": "attention.self.query.weight",
        "attention.self.query.bias": "attention.self.query.bias",
        "attention.self.key.kernel": "attention.self.key.weight",
        "attention.self.key.bias": "attention.self.key.bias",
        "attention.self.value.kernel": "attention.self.value.weight",
        "attention.self.value.bias": "attention.self.value.bias",
        "attention.output_dense.kernel": "attention.output.dense.weight",
        "attention.output_dense.bias": "attention.output.dense.bias",
        "attention.output_ln.scale": "attention.output.LayerNorm.weight",
        "attention.output_ln.bias": "attention.output.LayerNorm.bias",
        "intermediate.kernel": "intermediate.dense.weight",
        "intermediate.bias": "intermediate.dense.bias",
        "output.kernel": "output.dense.weight",
        "output.bias": "output.dense.bias",
        "output_ln.scale": "output.LayerNorm.weight",
        "output_ln.bias": "output.LayerNorm.bias",
    }
    top_map = {
        "word_embeddings": "bert.embeddings.word_embeddings.weight",
        "position_embeddings": "bert.embeddings.position_embeddings.weight",
        "token_type_embeddings":
            "bert.embeddings.token_type_embeddings.weight",
        "embeddings_ln.scale": "bert.embeddings.LayerNorm.weight",
        "embeddings_ln.bias": "bert.embeddings.LayerNorm.bias",
        "transform.kernel": "cls.predictions.transform.dense.weight",
        "transform.bias": "cls.predictions.transform.dense.bias",
        "transform_ln.scale": "cls.predictions.transform.LayerNorm.weight",
        "transform_ln.bias": "cls.predictions.transform.LayerNorm.bias",
        "decoder_bias": "cls.predictions.bias",
    }

    @staticmethod
    def lookup(sd, key):
        if key in sd:
            return sd[key]
        if key.startswith("bert.") and key[len("bert."):] in sd:
            return sd[key[len("bert."):]]
        return None

    def layer_key(self, i, suffix):
        return f"bert.encoder.layer.{i}.{suffix}"


class GPTNeoWeightMap(HFWeightMap):
    """HF ``GPTNeoForCausalLM``: separate bias-free q/k/v under
    ``attn.attention``, a biased out_proj, nn.Linear MLP (transpose),
    learned positions, tied head."""

    arch = "gpt-neo"
    layer_re = re.compile(r"^(?:transformer\.)?h\.(\d+)\.(.+)$")
    layer_map = {
        "ln_1.scale": "ln_1.weight", "ln_1.bias": "ln_1.bias",
        "c_proj.kernel": "attn.attention.out_proj.weight",
        "c_proj.bias": "attn.attention.out_proj.bias",
        "ln_2.scale": "ln_2.weight", "ln_2.bias": "ln_2.bias",
        "c_fc.kernel": "mlp.c_fc.weight", "c_fc.bias": "mlp.c_fc.bias",
        "mlp_c_proj.kernel": "mlp.c_proj.weight",
        "mlp_c_proj.bias": "mlp.c_proj.bias",
    }
    top_map = {
        "wte": "transformer.wte.weight", "wpe": "transformer.wpe.weight",
        "ln_f.scale": "transformer.ln_f.weight",
        "ln_f.bias": "transformer.ln_f.bias",
    }

    def layer_key(self, i, suffix):
        return f"transformer.h.{i}.{suffix}"

    def layer_weights(self, sd, i):
        out = super().layer_weights(sd, i)
        ws = [self.lookup(sd, self.layer_key(
            i, f"attn.attention.{n}_proj.weight")) for n in "qkv"]
        if all(w is not None for w in ws):
            qw, kw, vw = (np.ascontiguousarray(w.T) for w in ws)
            out["c_attn.kernel"] = merge_qkv(qw, kw, vw)
        return out


_WEIGHT_MAPS = {"gpt2": GPT2WeightMap, "opt": OPTWeightMap,
                "bloom": BloomWeightMap, "llama": LlamaWeightMap,
                "gptj": GPTJWeightMap, "gpt-neox": GPTNeoXWeightMap,
                "gpt-neo": GPTNeoWeightMap, "bert": BertWeightMap}


def get_weight_map(arch: str, **kw) -> HFWeightMap:
    if arch not in _WEIGHT_MAPS:
        raise ValueError(f"no weight map for arch {arch!r}; "
                         f"have {sorted(_WEIGHT_MAPS)}")
    return _WEIGHT_MAPS[arch](**kw)


def detect_arch(sd: Dict[str, Any]) -> Optional[str]:
    keys = list(sd)
    if any("attn.c_attn" in k for k in keys):
        return "gpt2"
    if any("self_attn.q_proj" in k and "decoder" in k for k in keys):
        return "opt"
    if any("self_attention.query_key_value" in k for k in keys):
        return "bloom"
    if any("mlp.gate_proj" in k for k in keys):
        return "llama"
    if any("mlp.fc_in" in k for k in keys):
        return "gptj"
    if any("attn.attention.q_proj" in k for k in keys):
        return "gpt-neo"
    if any("attention.query_key_value" in k for k in keys):
        return "gpt-neox"
    if any("attention.self.query" in k for k in keys):
        return "bert"
    if any(k.startswith("visual_projection")
           or "vision_model.encoder" in k for k in keys):
        return "clip"
    return None


# ----------------------------------------------------------------------
# Materialize into this framework's models


import functools


@functools.lru_cache(maxsize=32)
def _load_config_json(path: str):
    # open/parse errors propagate: a present-but-unreadable config.json
    # must not silently degrade to default hyperparameters
    import json

    with open(path) as f:
        return json.load(f)


def _sniff_config(src, *keys):
    """First matching value from the model dir's config.json (``src`` may
    be a dir, a file inside one, or a non-path — then None). The json is
    parsed once per path (lru-cached) however many keys get sniffed."""
    if not isinstance(src, (str, os.PathLike)):
        return None
    path = str(src)
    if not os.path.isdir(path):
        path = os.path.dirname(path)
    cfg_json = os.path.join(path, "config.json") if path else None
    if not cfg_json or not os.path.exists(cfg_json):
        return None
    hf = _load_config_json(cfg_json)
    for key in keys:
        if key in hf:
            return hf[key]
    return None


def load_hf_gpt2(src, scan_layers: bool = True, dtype=None,
                 n_head: Optional[int] = None):
    """HF GPT-2 checkpoint → (GPT2Config, flax params) for
    :class:`deepspeed_tpu.models.gpt2.GPT2LMHeadModel`.

    ``src``: HF model dir / checkpoint file / state_dict. ``n_head`` is read
    from the model dir's config.json when present (weights alone cannot
    reveal it); pass it explicitly for bare state_dicts with non-64 head
    dims. The returned params slot straight into
    ``initialize(model_parameters=...)`` or ``init_inference(params=...)``.
    """
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config

    if n_head is None:
        n_head = _sniff_config(src, "n_head", "num_attention_heads")
    sd = SDLoaderFactory.load(src)
    wm = GPT2WeightMap()
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    wte, wpe = top["wte"], top["wpe"]
    n_embd = wte.shape[1]
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    config = GPT2Config(
        vocab_size=wte.shape[0], n_positions=wpe.shape[0], n_embd=n_embd,
        n_layer=n_layer, n_head=n_head or max(1, n_embd // 64),
        dtype=dtype if dtype is not None else jnp.float32,
        scan_layers=scan_layers)

    params = _canonical_gpt2_tree(layers, top, scan_layers, wpe=wpe)
    logger.info(f"loaded HF GPT-2: {n_layer} layers, n_embd={n_embd}, "
                f"vocab={wte.shape[0]}")
    return config, params


def _canonical_gpt2_tree(layers, top, scan_layers, wpe=None, emb_ln=False,
                         attn_bias=True, attn_out_bias=None, has_ln_2=True,
                         untied_head=False):
    """Canonical per-layer dicts → the flax GPT2LMHeadModel param tree
    (the one model that executes the whole fused-c_attn decoder family).
    ``attn_bias=False`` (GPT-J) drops the attention bias leaves,
    ``has_ln_2=False`` (GPT-J single-LN parallel residual) drops ln_2, and
    ``untied_head`` adds the separate lm_head (+bias when present)."""

    def block_tree(lw):
        # direct indexing throughout: every arch this tree serves has all
        # the weights its flag set names — a missing one means a truncated
        # checkpoint and must fail loudly, not zero-fill
        attn = {"c_attn": {"kernel": lw["c_attn.kernel"]},
                "c_proj": {"kernel": lw["c_proj.kernel"]}}
        if attn_bias:
            attn["c_attn"]["bias"] = lw["c_attn.bias"]
        # same None-means-follow-attn_bias rule the model applies to c_proj
        if (attn_bias if attn_out_bias is None else attn_out_bias):
            attn["c_proj"]["bias"] = lw["c_proj.bias"]
        tree = {
            "ln_1": {"scale": lw["ln_1.scale"], "bias": lw["ln_1.bias"]},
            "attn": attn,
            "mlp": {"c_fc": {"kernel": lw["c_fc.kernel"],
                             "bias": lw["c_fc.bias"]},
                    "c_proj": {"kernel": lw["mlp_c_proj.kernel"],
                               "bias": lw["mlp_c_proj.bias"]}},
        }
        if has_ln_2:
            tree["ln_2"] = {"scale": lw["ln_2.scale"],
                            "bias": lw["ln_2.bias"]}
        return tree

    if scan_layers:
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, axis=0), *[block_tree(l) for l in layers])
        transformer = {"h": {"block": stacked}}
    else:
        transformer = {f"h_{i}": block_tree(l) for i, l in enumerate(layers)}
    params = {
        "wte": top["wte"],
        "ln_f": {"scale": top["ln_f.scale"], "bias": top["ln_f.bias"]},
        "transformer": transformer,
    }
    if wpe is not None:
        params["wpe"] = wpe
    if emb_ln:
        params["emb_ln"] = {"scale": top["emb_ln.scale"],
                            "bias": top["emb_ln.bias"]}
    if untied_head:
        params["lm_head"] = top["lm_head"]
        if "lm_head_bias" in top:
            params["lm_head_bias"] = top["lm_head_bias"]
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32), params)


def load_hf_opt(src, scan_layers: bool = True, dtype=None,
                n_head: Optional[int] = None):
    """HF ``OPTForCausalLM`` checkpoint → (GPT2Config, flax params): the
    canonical decoder runs OPT as relu activation + learned positions with
    the 2-row pad offset HF's embed_positions carries. (Pre-LN variants
    only — the 350m post-LN oddity is not supported.)"""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config

    if n_head is None:
        n_head = _sniff_config(src, "num_attention_heads", "n_head")
    if n_head is None:
        # unlike GPT-2's uniform head_dim-64, real OPT sizes (2.7b+) use
        # head_dim 80 — a silent guess divides evenly and produces wrong
        # logits with no error
        raise ValueError("load_hf_opt needs n_head (config.json or arg)")
    sd = SDLoaderFactory.load(src)
    wm = OPTWeightMap()
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    wte, wpe = top["wte"], top["wpe"]
    n_embd = wte.shape[1]
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    config = GPT2Config(
        vocab_size=wte.shape[0], n_positions=wpe.shape[0] - 2,
        n_embd=n_embd, n_layer=n_layer, n_head=n_head,
        activation="relu", position_offset=2,
        dtype=dtype if dtype is not None else jnp.float32,
        scan_layers=scan_layers)
    params = _canonical_gpt2_tree(layers, top, scan_layers, wpe=wpe)
    logger.info(f"loaded HF OPT: {n_layer} layers, n_embd={n_embd}, "
                f"vocab={wte.shape[0]}")
    return config, params


def load_hf_bloom(src, scan_layers: bool = True, dtype=None,
                  n_head: Optional[int] = None,
                  max_positions: int = 2048):
    """HF ``BloomForCausalLM`` checkpoint → (GPT2Config, flax params): the
    canonical decoder runs BLOOM as ALiBi positions (no table), gelu, and
    the word-embedding layernorm; QKV is de-interleaved by the weight map.
    ``n_head`` is required for bare state_dicts (ALiBi slopes and the QKV
    layout both depend on it)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config

    if n_head is None:
        n_head = _sniff_config(src, "n_head", "num_attention_heads")
    if n_head is None:
        raise ValueError("load_hf_bloom needs n_head (config.json or arg): "
                         "ALiBi slopes and QKV de-interleave depend on it")
    sd = SDLoaderFactory.load(src)
    wm = BloomWeightMap(n_head=n_head)
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    wte = top["wte"]
    n_embd = wte.shape[1]
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    config = GPT2Config(
        vocab_size=wte.shape[0], n_positions=max_positions,
        n_embd=n_embd, n_layer=n_layer, n_head=n_head,
        position_embedding="alibi", embedding_layernorm=True,
        dtype=dtype if dtype is not None else jnp.float32,
        scan_layers=scan_layers)
    params = _canonical_gpt2_tree(layers, top, scan_layers, emb_ln=True)
    logger.info(f"loaded HF BLOOM: {n_layer} layers, n_embd={n_embd}, "
                f"vocab={wte.shape[0]}, alibi heads={n_head}")
    return config, params


def load_hf_gptj(src, scan_layers: bool = True, dtype=None,
                 n_head: Optional[int] = None,
                 rotary_dim: Optional[int] = None,
                 n_positions: Optional[int] = None):
    """HF ``GPTJForCausalLM`` checkpoint → (GPT2Config, flax params): the
    canonical decoder runs GPT-J as partial interleaved rotary positions,
    bias-free attention, single-LN parallel residual, and an untied lm_head
    with bias (reference arch policy: module_inject/replace_policy.py
    GPTJ entry)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config

    if n_head is None:
        n_head = _sniff_config(src, "n_head", "num_attention_heads")
    if n_head is None:
        raise ValueError("load_hf_gptj needs n_head (config.json or arg): "
                         "GPT-J's head_dim 256 breaks the 64-dim guess")
    if rotary_dim is None:
        rotary_dim = _sniff_config(src, "rotary_dim")
    if rotary_dim is None:
        # every real GPT-J checkpoint rotates a PARTIAL head slice (64 of
        # 256); defaulting to full-head rotation would be silently wrong
        raise ValueError("load_hf_gptj needs rotary_dim (config.json or "
                         "arg): GPT-J rotates a partial head slice")
    if n_positions is None:
        n_positions = _sniff_config(src, "n_positions") or 2048
    sd = SDLoaderFactory.load(src)
    wm = GPTJWeightMap()
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    wte = top["wte"]
    n_embd = wte.shape[1]
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    config = GPT2Config(
        vocab_size=wte.shape[0], n_positions=n_positions,
        n_embd=n_embd, n_layer=n_layer, n_head=n_head,
        position_embedding="rotary", rotary_dim=rotary_dim,
        rotary_interleaved=True, residual="parallel_single_ln",
        attn_bias=False, tied_head=False,
        lm_head_bias="lm_head_bias" in top,
        dtype=dtype if dtype is not None else jnp.float32,
        scan_layers=scan_layers)
    params = _canonical_gpt2_tree(layers, top, scan_layers, attn_bias=False,
                                  has_ln_2=False, untied_head=True)
    logger.info(f"loaded HF GPT-J: {n_layer} layers, n_embd={n_embd}, "
                f"vocab={wte.shape[0]}, rotary_dim={rotary_dim}")
    return config, params


def _expand_attention_types(attention_types, n_layer: int):
    """Normalize GPT-Neo attention-type declarations: the expanded
    per-layer list (config.attention_layers) passes through; HF's compact
    ``[[["global", "local"], N]]`` form (config.attention_types) expands.
    Unknown entries raise — a typo silently running global attention on
    every layer produces wrong logits with no error."""
    out = []
    for t in attention_types:
        if isinstance(t, (list, tuple)):
            kinds, count = t
            out.extend(list(kinds) * int(count))
        else:
            out.append(t)
    bad = {t for t in out if t not in ("global", "local")}
    if bad:
        raise ValueError(f"unknown attention types {sorted(bad)}; "
                         "expected 'global'/'local'")
    if len(out) != n_layer:
        raise ValueError(f"attention_types expands to {len(out)} layers "
                         f"but the checkpoint has {n_layer}")
    return out


def load_hf_gpt_neo(src, scan_layers: bool = False, dtype=None,
                    n_head: Optional[int] = None,
                    attention_types=None, window_size: Optional[int] = None):
    """HF ``GPTNeoForCausalLM`` checkpoint → (GPT2Config, flax params): the
    canonical decoder runs GPT-Neo as learned positions, UNSCALED attention
    logits, bias-free q/k/v with a biased out-projection, and alternating
    global/local (sliding-window) attention layers — which forces the
    unrolled layout (per-layer windows are static properties;
    ``scan_layers=True`` is rejected rather than silently ignored)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config

    if scan_layers:
        raise ValueError(
            "GPT-Neo's alternating local/global attention needs the "
            "unrolled layout: call with scan_layers=False")
    if n_head is None:
        n_head = _sniff_config(src, "num_heads", "num_attention_heads")
    if n_head is None:
        raise ValueError("load_hf_gpt_neo needs n_head (config.json or arg)")
    if attention_types is None:
        at = _sniff_config(src, "attention_layers")
        attention_types = list(at) if at is not None else None
    if window_size is None:
        window_size = _sniff_config(src, "window_size") or 256
    sd = SDLoaderFactory.load(src)
    wm = GPTNeoWeightMap()
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    wte, wpe = top["wte"], top["wpe"]
    n_embd = wte.shape[1]
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    if attention_types is None:
        # HF default: global/local alternating starting global
        attention_types = ["global" if i % 2 == 0 else "local"
                           for i in range(n_layer)]
    attention_types = _expand_attention_types(attention_types, n_layer)
    windows = tuple(int(window_size) if t == "local" else 0
                    for t in attention_types)
    config = GPT2Config(
        vocab_size=wte.shape[0], n_positions=wpe.shape[0], n_embd=n_embd,
        n_layer=n_layer, n_head=n_head,
        attn_bias=False, attn_out_bias=True, attn_scale=1.0,
        attention_windows=windows, scan_layers=False,
        dtype=dtype if dtype is not None else jnp.float32)
    params = _canonical_gpt2_tree(layers, top, scan_layers=False, wpe=wpe,
                                  attn_bias=False, attn_out_bias=True)
    logger.info(f"loaded HF GPT-Neo: {n_layer} layers, n_embd={n_embd}, "
                f"vocab={wte.shape[0]}, windows={windows}")
    return config, params


def load_hf_gpt_neox(src, scan_layers: bool = True, dtype=None,
                     n_head: Optional[int] = None,
                     rotary_pct: Optional[float] = None,
                     rope_theta: Optional[float] = None,
                     use_parallel_residual: Optional[bool] = None,
                     max_positions: Optional[int] = None):
    """HF ``GPTNeoXForCausalLM`` checkpoint → (GPT2Config, flax params):
    the canonical decoder runs NeoX as partial rotate-half rotary, two-LN
    parallel residual (or sequential when the checkpoint trained with
    ``use_parallel_residual=false``), exact gelu, and the untied
    ``embed_out`` head (reference arch policy: replace_policy.py GPTNEOX
    entry)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config

    if n_head is None:
        n_head = _sniff_config(src, "num_attention_heads", "n_head")
    if n_head is None:
        raise ValueError("load_hf_gpt_neox needs n_head (config.json or "
                         "arg): the fused-QKV de-interleave depends on it")
    if rotary_pct is None:
        rotary_pct = _sniff_config(src, "rotary_pct")
        rotary_pct = 1.0 if rotary_pct is None else rotary_pct
    if rope_theta is None:
        rope_theta = _sniff_config(src, "rotary_emb_base") or 10000.0
    if use_parallel_residual is None:
        v = _sniff_config(src, "use_parallel_residual")
        use_parallel_residual = True if v is None else bool(v)
    if max_positions is None:
        max_positions = _sniff_config(src, "max_position_embeddings") or 2048
    sd = SDLoaderFactory.load(src)
    wm = GPTNeoXWeightMap(n_head=n_head)
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    wte = top["wte"]
    n_embd = wte.shape[1]
    head_dim = n_embd // n_head
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    config = GPT2Config(
        vocab_size=wte.shape[0], n_positions=max_positions,
        n_embd=n_embd, n_layer=n_layer, n_head=n_head,
        position_embedding="rotary",
        rotary_dim=int(head_dim * rotary_pct),
        rotary_interleaved=False, rope_theta=float(rope_theta),
        residual="parallel_two_ln" if use_parallel_residual
        else "sequential",
        activation="gelu_exact", tied_head=False,
        dtype=dtype if dtype is not None else jnp.float32,
        scan_layers=scan_layers)
    params = _canonical_gpt2_tree(layers, top, scan_layers,
                                  untied_head=True)
    logger.info(f"loaded HF GPT-NeoX: {n_layer} layers, n_embd={n_embd}, "
                f"vocab={wte.shape[0]}, rotary_dim={config.rotary_dim}, "
                f"parallel_residual={use_parallel_residual}")
    return config, params


def _nest_dotted(flat: Dict[str, np.ndarray]) -> Dict:
    """{'a.b.c': w} → {'a': {'b': {'c': w}}} (canonical dotted names →
    flax param nesting)."""
    out: Dict = {}
    for key, w in flat.items():
        node = out
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = w
    return out


def load_hf_bert(src, scan_layers: bool = True, dtype=None,
                 num_attention_heads: Optional[int] = None):
    """HF ``BertForMaskedLM`` checkpoint → (BertConfig, flax params) for
    :class:`deepspeed_tpu.models.bert.BertForMaskedLM` (the reference's
    marquee kernel target — BASELINE.md BERT rows)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.bert import BertConfig

    if num_attention_heads is None:
        num_attention_heads = _sniff_config(src, "num_attention_heads")
    if num_attention_heads is None:
        # same stance as load_hf_opt: a silent head_dim-64 guess reshapes
        # attention across head boundaries and fails parity with no error
        raise ValueError("load_hf_bert needs num_attention_heads "
                         "(config.json or arg)")
    sd = SDLoaderFactory.load(src)
    wm = BertWeightMap()
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    wte = top["word_embeddings"]
    hidden = wte.shape[1]
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    inter = layers[0]["intermediate.kernel"].shape[-1]
    config = BertConfig(
        vocab_size=wte.shape[0], hidden_size=hidden,
        num_hidden_layers=n_layer,
        num_attention_heads=num_attention_heads,
        intermediate_size=inter,
        max_position_embeddings=top["position_embeddings"].shape[0],
        type_vocab_size=top["token_type_embeddings"].shape[0],
        dtype=dtype if dtype is not None else jnp.float32,
        scan_layers=scan_layers)

    block_trees = [_nest_dotted(lw) for lw in layers]
    if scan_layers:
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, axis=0), *block_trees)
        encoder = {"layers": {"layer": stacked}}
    else:
        encoder = {f"layer_{i}": t for i, t in enumerate(block_trees)}
    params = {
        "bert": {
            "word_embeddings": wte,
            "position_embeddings": top["position_embeddings"],
            "token_type_embeddings": top["token_type_embeddings"],
            "embeddings_ln": {"scale": top["embeddings_ln.scale"],
                              "bias": top["embeddings_ln.bias"]},
            "encoder": encoder,
        },
        "transform": {"kernel": top["transform.kernel"],
                      "bias": top["transform.bias"]},
        "transform_ln": {"scale": top["transform_ln.scale"],
                         "bias": top["transform_ln.bias"]},
        "decoder_bias": top["decoder_bias"],
    }
    params = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32), params)
    logger.info(f"loaded HF BERT: {n_layer} layers, hidden={hidden}, "
                f"vocab={wte.shape[0]}")
    return config, params


def load_hf_llama(src, scan_layers: bool = True, dtype=None,
                  num_attention_heads: Optional[int] = None,
                  num_key_value_heads: Optional[int] = None,
                  rope_theta: Optional[float] = None,
                  rms_norm_eps: Optional[float] = None,
                  max_position_embeddings: Optional[int] = None):
    """HF Llama checkpoint → (LlamaConfig, flax params) for
    :class:`deepspeed_tpu.models.llama.LlamaModel`. For every config knob
    an explicit argument wins; unset knobs come from the model dir's
    config.json when present, else the Llama-2 defaults. Pass head counts
    for bare state_dicts (k_proj's out-dim reveals kv heads only up to
    head_dim)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.llama import LlamaConfig

    num_attention_heads = (num_attention_heads
                           or _sniff_config(src, "num_attention_heads"))
    num_key_value_heads = (num_key_value_heads
                           or _sniff_config(src, "num_key_value_heads"))
    if rope_theta is None:
        rope_theta = _sniff_config(src, "rope_theta")
    if rms_norm_eps is None:
        rms_norm_eps = _sniff_config(src, "rms_norm_eps")
    max_position_embeddings = (max_position_embeddings or _sniff_config(
        src, "max_position_embeddings"))
    rope_theta = 10000.0 if rope_theta is None else rope_theta
    rms_norm_eps = 1e-5 if rms_norm_eps is None else rms_norm_eps
    sd = SDLoaderFactory.load(src)
    wm = LlamaWeightMap()
    n_layer = wm.n_layers(sd)
    top = wm.top_weights(sd)
    embed = top["embed_tokens"]
    hidden = embed.shape[1]
    layers = [wm.layer_weights(sd, i) for i in range(n_layer)]
    inter = layers[0]["mlp.gate_proj.kernel"].shape[1]
    heads = num_attention_heads or max(1, hidden // 128)
    kv_dim = layers[0]["self_attn.k_proj.kernel"].shape[1]
    kv_heads = num_key_value_heads or max(1, kv_dim // (hidden // heads))
    tied = "lm_head" not in top
    config = LlamaConfig(
        vocab_size=embed.shape[0], hidden_size=hidden,
        intermediate_size=inter, num_hidden_layers=n_layer,
        num_attention_heads=heads, num_key_value_heads=kv_heads,
        rope_theta=rope_theta, rms_norm_eps=rms_norm_eps,
        max_position_embeddings=max_position_embeddings or 4096,
        tie_word_embeddings=tied,
        dtype=dtype if dtype is not None else jnp.float32,
        scan_layers=scan_layers)

    def block_tree(lw):
        return {
            "input_layernorm": {"scale": lw["input_layernorm.scale"]},
            "post_attention_layernorm": {
                "scale": lw["post_attention_layernorm.scale"]},
            "self_attn": {n: {"kernel": lw[f"self_attn.{n}.kernel"]}
                          for n in ("q_proj", "k_proj", "v_proj", "o_proj")},
            "mlp": {n: {"kernel": lw[f"mlp.{n}.kernel"]}
                    for n in ("gate_proj", "up_proj", "down_proj")},
        }

    if scan_layers:
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, axis=0), *[block_tree(l) for l in layers])
        body = {"layers": {"block": stacked}}
    else:
        body = {f"layers_{i}": block_tree(l) for i, l in enumerate(layers)}
    params = {"embed_tokens": embed, "norm": {"scale": top["norm.scale"]},
              **body}
    if not tied:
        params["lm_head"] = top["lm_head"]
    params = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32), params)
    logger.info(f"loaded HF Llama: {n_layer} layers, hidden={hidden}, "
                f"heads={heads}/{kv_heads}kv, vocab={embed.shape[0]}")
    return config, params


# ----------------------------------------------------------------------
# Export: flax params → HF state dicts (the interop inverse of the
# loaders; reference capability: ``save_16bit_model``/``zero_to_fp32``
# produce reference-consumable checkpoints — these produce
# transformers-consumable ones)


def _f32(a):
    return np.ascontiguousarray(np.asarray(a, np.float32))


def _blocks_list(container, scanned_path, unrolled_prefix):
    """Per-layer trees from either layout: the scanned stack (leading layer
    axis) or ``<prefix>_i`` siblings. Zero layers or an index gap is a
    LAYOUT ERROR, not an empty model — a silently truncated export would
    pass ``load_state_dict(strict=False)`` and produce garbage logits."""
    node = container
    for seg in scanned_path:
        node = node.get(seg, {}) if isinstance(node, dict) else {}
    if node:  # scanned: every leaf carries the layer axis
        n = int(jax.tree_util.tree_leaves(node)[0].shape[0])
        return [jax.tree_util.tree_map(lambda a, i=i: np.asarray(a)[i],
                                       node) for i in range(n)]
    pre = unrolled_prefix + "_"
    idxs = sorted(int(k[len(pre):]) for k in container
                  if k.startswith(pre) and k[len(pre):].isdigit())
    if not idxs or idxs != list(range(len(idxs))):
        raise ValueError(
            f"no transformer layers found under "
            f"{'/'.join(scanned_path)!r} or contiguous "
            f"{unrolled_prefix}_i keys (got indices {idxs}); the params "
            "tree does not match this exporter's expected layout")
    return [container[f"{pre}{i}"] for i in idxs]


def export_hf_gpt2(params) -> Dict[str, np.ndarray]:
    """Canonical GPT-2 params → HF ``GPT2LMHeadModel`` state dict (plain
    GPT-2 layout only: tied head, learned positions; Conv1D keeps the
    [in, out] orientation so kernels pass through untransposed)."""
    wte = _f32(params["wte"])
    sd = {
        "transformer.wte.weight": wte,
        "transformer.wpe.weight": _f32(params["wpe"]),
        "transformer.ln_f.weight": _f32(params["ln_f"]["scale"]),
        "transformer.ln_f.bias": _f32(params["ln_f"]["bias"]),
        "lm_head.weight": wte,  # tied: same array, HF re-ties on load
    }
    for i, b in enumerate(_blocks_list(params.get("transformer", {}),
                                       ("h", "block"), "h")):
        p = f"transformer.h.{i}."
        sd[p + "ln_1.weight"] = _f32(b["ln_1"]["scale"])
        sd[p + "ln_1.bias"] = _f32(b["ln_1"]["bias"])
        sd[p + "attn.c_attn.weight"] = _f32(b["attn"]["c_attn"]["kernel"])
        sd[p + "attn.c_attn.bias"] = _f32(b["attn"]["c_attn"]["bias"])
        sd[p + "attn.c_proj.weight"] = _f32(b["attn"]["c_proj"]["kernel"])
        sd[p + "attn.c_proj.bias"] = _f32(b["attn"]["c_proj"]["bias"])
        sd[p + "ln_2.weight"] = _f32(b["ln_2"]["scale"])
        sd[p + "ln_2.bias"] = _f32(b["ln_2"]["bias"])
        sd[p + "mlp.c_fc.weight"] = _f32(b["mlp"]["c_fc"]["kernel"])
        sd[p + "mlp.c_fc.bias"] = _f32(b["mlp"]["c_fc"]["bias"])
        sd[p + "mlp.c_proj.weight"] = _f32(b["mlp"]["c_proj"]["kernel"])
        sd[p + "mlp.c_proj.bias"] = _f32(b["mlp"]["c_proj"]["bias"])
    return sd


def export_hf_llama(params) -> Dict[str, np.ndarray]:
    """Llama params → HF ``LlamaForCausalLM`` state dict (flax [in, out]
    kernels transpose back to nn.Linear's [out, in])."""
    embed = _f32(params["embed_tokens"])
    sd = {
        "model.embed_tokens.weight": embed,
        "model.norm.weight": _f32(params["norm"]["scale"]),
        # untied: own matrix; tied: the same array (HF re-ties on load)
        "lm_head.weight": (_f32(params["lm_head"])
                           if "lm_head" in params else embed),
    }
    for i, b in enumerate(_blocks_list(params, ("layers", "block"),
                                       "layers")):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = _f32(
            b["input_layernorm"]["scale"])
        sd[p + "post_attention_layernorm.weight"] = _f32(
            b["post_attention_layernorm"]["scale"])
        for n in ("q_proj", "k_proj", "v_proj", "o_proj"):
            sd[p + f"self_attn.{n}.weight"] = _f32(
                b["self_attn"][n]["kernel"].T)
        for n in ("gate_proj", "up_proj", "down_proj"):
            sd[p + f"mlp.{n}.weight"] = _f32(b["mlp"][n]["kernel"].T)
    return sd


def export_hf_bert(params) -> Dict[str, np.ndarray]:
    """BERT params → HF ``BertForMaskedLM`` state dict."""
    bert = params["bert"]
    wte = _f32(bert["word_embeddings"])
    dec_bias = _f32(params["decoder_bias"])
    sd = {
        "bert.embeddings.word_embeddings.weight": wte,
        "bert.embeddings.position_embeddings.weight":
            _f32(bert["position_embeddings"]),
        "bert.embeddings.token_type_embeddings.weight":
            _f32(bert["token_type_embeddings"]),
        "bert.embeddings.LayerNorm.weight": _f32(
            bert["embeddings_ln"]["scale"]),
        "bert.embeddings.LayerNorm.bias": _f32(
            bert["embeddings_ln"]["bias"]),
        "cls.predictions.transform.dense.weight": _f32(
            params["transform"]["kernel"].T),
        "cls.predictions.transform.dense.bias": _f32(
            params["transform"]["bias"]),
        "cls.predictions.transform.LayerNorm.weight": _f32(
            params["transform_ln"]["scale"]),
        "cls.predictions.transform.LayerNorm.bias": _f32(
            params["transform_ln"]["bias"]),
        "cls.predictions.bias": dec_bias,
        "cls.predictions.decoder.weight": wte,  # tied
        "cls.predictions.decoder.bias": dec_bias,
    }
    for i, b in enumerate(_blocks_list(bert.get("encoder", {}),
                                       ("layers", "layer"), "layer")):
        p = f"bert.encoder.layer.{i}."
        for n in ("query", "key", "value"):
            sd[p + f"attention.self.{n}.weight"] = _f32(
                b["attention"]["self"][n]["kernel"].T)
            sd[p + f"attention.self.{n}.bias"] = _f32(
                b["attention"]["self"][n]["bias"])
        sd[p + "attention.output.dense.weight"] = _f32(
            b["attention"]["output_dense"]["kernel"].T)
        sd[p + "attention.output.dense.bias"] = _f32(
            b["attention"]["output_dense"]["bias"])
        sd[p + "attention.output.LayerNorm.weight"] = _f32(
            b["attention"]["output_ln"]["scale"])
        sd[p + "attention.output.LayerNorm.bias"] = _f32(
            b["attention"]["output_ln"]["bias"])
        sd[p + "intermediate.dense.weight"] = _f32(
            b["intermediate"]["kernel"].T)
        sd[p + "intermediate.dense.bias"] = _f32(b["intermediate"]["bias"])
        sd[p + "output.dense.weight"] = _f32(b["output"]["kernel"].T)
        sd[p + "output.dense.bias"] = _f32(b["output"]["bias"])
        sd[p + "output.LayerNorm.weight"] = _f32(b["output_ln"]["scale"])
        sd[p + "output.LayerNorm.bias"] = _f32(b["output_ln"]["bias"])
    return sd


def export_hf_opt(params) -> Dict[str, np.ndarray]:
    """Canonical OPT params → HF ``OPTForCausalLM`` state dict: the fused
    c_attn splits back into q/k/v and kernels transpose to nn.Linear's
    [out, in] (inverse of OPTWeightMap)."""
    wte = _f32(params["wte"])
    sd = {
        "model.decoder.embed_tokens.weight": wte,
        "model.decoder.embed_positions.weight": _f32(params["wpe"]),
        "model.decoder.final_layer_norm.weight": _f32(
            params["ln_f"]["scale"]),
        "model.decoder.final_layer_norm.bias": _f32(params["ln_f"]["bias"]),
        "lm_head.weight": wte,  # tied
    }
    for i, b in enumerate(_blocks_list(params.get("transformer", {}),
                                       ("h", "block"), "h")):
        p = f"model.decoder.layers.{i}."
        sd[p + "self_attn_layer_norm.weight"] = _f32(b["ln_1"]["scale"])
        sd[p + "self_attn_layer_norm.bias"] = _f32(b["ln_1"]["bias"])
        qw, kw, vw = split_qkv(np.asarray(b["attn"]["c_attn"]["kernel"]))
        qb, kb, vb = split_qkv(np.asarray(b["attn"]["c_attn"]["bias"]))
        for n, w, bias in (("q", qw, qb), ("k", kw, kb), ("v", vw, vb)):
            sd[p + f"self_attn.{n}_proj.weight"] = _f32(w.T)
            sd[p + f"self_attn.{n}_proj.bias"] = _f32(bias)
        sd[p + "self_attn.out_proj.weight"] = _f32(
            np.asarray(b["attn"]["c_proj"]["kernel"]).T)
        sd[p + "self_attn.out_proj.bias"] = _f32(b["attn"]["c_proj"]["bias"])
        sd[p + "final_layer_norm.weight"] = _f32(b["ln_2"]["scale"])
        sd[p + "final_layer_norm.bias"] = _f32(b["ln_2"]["bias"])
        sd[p + "fc1.weight"] = _f32(np.asarray(b["mlp"]["c_fc"]["kernel"]).T)
        sd[p + "fc1.bias"] = _f32(b["mlp"]["c_fc"]["bias"])
        sd[p + "fc2.weight"] = _f32(
            np.asarray(b["mlp"]["c_proj"]["kernel"]).T)
        sd[p + "fc2.bias"] = _f32(b["mlp"]["c_proj"]["bias"])
    return sd


def _interleave_bloom_qkv(w: np.ndarray, n_head: int) -> np.ndarray:
    """Inverse of :func:`deinterleave_bloom_qkv`: canonical Q|K|V concat →
    BLOOM's per-head [h0q, h0k, h0v, h1q, ...] packing ([..., 3C])."""
    *lead, out = w.shape
    c = out // 3
    hd = c // n_head
    q, k, v = (x.reshape(*lead, n_head, hd)
               for x in np.split(w, 3, axis=-1))
    return np.stack([q, k, v], axis=-2).reshape(*lead, out)


def export_hf_bloom(params, n_head: int) -> Dict[str, np.ndarray]:
    """Canonical BLOOM params → HF ``BloomForCausalLM`` state dict:
    QKV re-interleaves per head (``n_head`` required for the packing)."""
    wte = _f32(params["wte"])
    sd = {
        "transformer.word_embeddings.weight": wte,
        "transformer.word_embeddings_layernorm.weight": _f32(
            params["emb_ln"]["scale"]),
        "transformer.word_embeddings_layernorm.bias": _f32(
            params["emb_ln"]["bias"]),
        "transformer.ln_f.weight": _f32(params["ln_f"]["scale"]),
        "transformer.ln_f.bias": _f32(params["ln_f"]["bias"]),
        "lm_head.weight": wte,  # tied
    }
    for i, b in enumerate(_blocks_list(params.get("transformer", {}),
                                       ("h", "block"), "h")):
        p = f"transformer.h.{i}."
        sd[p + "input_layernorm.weight"] = _f32(b["ln_1"]["scale"])
        sd[p + "input_layernorm.bias"] = _f32(b["ln_1"]["bias"])
        sd[p + "self_attention.query_key_value.weight"] = _f32(
            _interleave_bloom_qkv(
                np.asarray(b["attn"]["c_attn"]["kernel"]), n_head).T)
        sd[p + "self_attention.query_key_value.bias"] = _f32(
            _interleave_bloom_qkv(
                np.asarray(b["attn"]["c_attn"]["bias"])[None], n_head)[0])
        sd[p + "self_attention.dense.weight"] = _f32(
            np.asarray(b["attn"]["c_proj"]["kernel"]).T)
        sd[p + "self_attention.dense.bias"] = _f32(
            b["attn"]["c_proj"]["bias"])
        sd[p + "post_attention_layernorm.weight"] = _f32(b["ln_2"]["scale"])
        sd[p + "post_attention_layernorm.bias"] = _f32(b["ln_2"]["bias"])
        sd[p + "mlp.dense_h_to_4h.weight"] = _f32(
            np.asarray(b["mlp"]["c_fc"]["kernel"]).T)
        sd[p + "mlp.dense_h_to_4h.bias"] = _f32(b["mlp"]["c_fc"]["bias"])
        sd[p + "mlp.dense_4h_to_h.weight"] = _f32(
            np.asarray(b["mlp"]["c_proj"]["kernel"]).T)
        sd[p + "mlp.dense_4h_to_h.bias"] = _f32(b["mlp"]["c_proj"]["bias"])
    return sd


_EXPORTERS = {"gpt2": export_hf_gpt2, "llama": export_hf_llama,
              "bert": export_hf_bert, "opt": export_hf_opt,
              "bloom": export_hf_bloom}


def _plain_dicts(tree):
    """Any Mapping (flax FrozenDict included) → plain nested dicts: the
    exporters walk with dict methods and an isinstance(dict) check."""
    from collections.abc import Mapping

    if isinstance(tree, Mapping):
        return {k: _plain_dicts(v) for k, v in tree.items()}
    return tree


def export_hf_state_dict(params, arch: str, **kw) -> Dict[str, np.ndarray]:
    """Flax params → HF-named numpy state dict for a supported arch.
    ``kw`` forwards arch-specific requirements (bloom: ``n_head`` for the
    per-head QKV re-interleave)."""
    params = _plain_dicts(jax.device_get(params))
    if arch not in _EXPORTERS:
        raise ValueError(f"no HF exporter for arch {arch!r}; "
                         f"have {sorted(_EXPORTERS)}")
    return _EXPORTERS[arch](params, **kw)


# ----------------------------------------------------------------------
# Megatron-style TP-degree reshaping over checkpoint shard LISTS
# (reference MegatronSDLoader, state_dict_factory.py:214: N shards saved
# at mp_size=M serve any mp_world_size W — each rank merges M/W files or
# slices 1/(W/M) of one file). The quantize-on-load path the reference
# folds in here is served separately by the inference engine's int8
# weight-only quantizer.

# key-pattern → reshard rule for Megatron GPT checkpoints (the reference's
# merge_state_dict/split_state_dict key tests, expressed as a table)
_MEGATRON_ROW_CAT = ("mlp.dense_h_to_4h.weight", "mlp.dense_h_to_4h.bias",
                     "word_embeddings.weight")   # output-dim parallel: axis 0
_MEGATRON_COL_CAT = ("attention.dense.weight",
                     "mlp.dense_4h_to_h.weight")  # input-dim parallel: axis 1
_MEGATRON_QKV = ("attention.query_key_value",)


def _merge_megatron_qkv(parts, version: float):
    """Per-rank fused QKV shards → one fused tensor.

    Version 0 stores ``[3 * np * hn, h]`` (Q rows of every rank first,
    then K, then V): the merge regroups per-projection before
    concatenating. Versions 1.0/2.0 store each rank's rows contiguously
    (``[np * hn * 3, h]`` / ``[np * 3 * hn, h]``), so plain axis-0 concat
    is already correct."""
    if version == 0:
        per_rank = [np.split(p, 3, axis=0) for p in parts]
        return np.concatenate(
            [np.concatenate([r[i] for r in per_rank], axis=0)
             for i in range(3)], axis=0)
    if version in (1.0, 2.0):
        return np.concatenate(parts, axis=0)
    raise ValueError(f"unsupported Megatron checkpoint version {version}")


def _split_megatron_qkv(value, num_to_split: int, offset: int,
                        version: float):
    """Inverse of :func:`_merge_megatron_qkv` for one target shard."""
    if version == 0:
        q, k, v = np.split(value, 3, axis=0)
        return np.concatenate(
            [np.split(t, num_to_split, axis=0)[offset] for t in (q, k, v)],
            axis=0)
    if version in (1.0, 2.0):
        return np.split(value, num_to_split, axis=0)[offset]
    raise ValueError(f"unsupported Megatron checkpoint version {version}")


class MegatronSDLoader:
    """Serve a Megatron GPT checkpoint shard list at any TP degree.

    ``ckpt_list`` entries may be file paths (anything
    :meth:`SDLoaderFactory.load` reads) or pre-loaded dicts; entries
    wrapped as ``{"module": ...}`` / ``{"model": ...}`` (Megatron-LM's
    on-disk nesting) are unwrapped and re-wrapped transparently.
    ``version`` overrides the shards' own ``checkpoint_version`` field.
    """

    def __init__(self, ckpt_list, version: Optional[float] = None):
        if not ckpt_list:
            raise ValueError("empty checkpoint list")
        self.ckpt_list = list(ckpt_list)
        self._version = version

    # -- shard IO -------------------------------------------------------
    def _load(self, entry):
        raw = entry if isinstance(entry, dict) else None
        if raw is None:
            import torch

            raw = torch.load(str(entry), map_location="cpu",
                             weights_only=False) \
                if str(entry).endswith((".pt", ".bin")) else None
        if raw is None:
            raw = SDLoaderFactory.load(entry)
        key = next((k for k in ("module", "model") if k in raw), None)
        module = raw[key] if key else raw
        version = self._version
        if version is None:
            version = float(raw.get("checkpoint_version", 0)
                            if isinstance(raw, dict) else 0)
        module = {k: _to_numpy(v) for k, v in module.items()}
        return module, key, version

    @staticmethod
    def _rule(key: str) -> str:
        if any(p in key for p in _MEGATRON_QKV):
            return "qkv"
        if any(p in key for p in _MEGATRON_ROW_CAT):
            return "row"
        if any(p in key for p in _MEGATRON_COL_CAT):
            return "col"
        return "replicated"

    # -- public API (reference SDLoaderBase.load contract) -------------
    def load(self, mp_world_size: int, mp_rank: int):
        """This rank's state dict at the requested TP degree."""
        n = len(self.ckpt_list)
        if n == mp_world_size:
            module, key, _ = self._load(self.ckpt_list[mp_rank])
            return {key: module} if key else module
        if n > mp_world_size:
            return self.merge_state_dict(mp_world_size, mp_rank)
        return self.split_state_dict(mp_world_size, mp_rank)

    def merge_state_dict(self, mp_world_size: int, mp_rank: int):
        n = len(self.ckpt_list)
        if n % mp_world_size != 0:
            raise ValueError(
                f"{n} checkpoint shards cannot merge onto "
                f"mp_world_size={mp_world_size}")
        group = n // mp_world_size
        loaded = [self._load(e) for e in
                  self.ckpt_list[group * mp_rank:group * (mp_rank + 1)]]
        mods = [m for m, _, _ in loaded]
        key, version = loaded[0][1], loaded[0][2]
        out = {}
        for k in mods[0]:
            parts = [m[k] for m in mods]
            rule = self._rule(k)
            if rule == "qkv":
                out[k] = _merge_megatron_qkv(parts, version)
            elif rule == "row":
                out[k] = np.concatenate(parts, axis=0)
            elif rule == "col":
                out[k] = (np.concatenate(parts, axis=1)
                          if parts[0].ndim > 1 else parts[0])
            else:
                out[k] = parts[0]
        return {key: out} if key else out

    def split_state_dict(self, mp_world_size: int, mp_rank: int):
        n = len(self.ckpt_list)
        if mp_world_size % n != 0:
            raise ValueError(
                f"{n} checkpoint shards cannot split onto "
                f"mp_world_size={mp_world_size}")
        num_to_split = mp_world_size // n
        module, key, version = self._load(
            self.ckpt_list[mp_rank // num_to_split])
        offset = mp_rank % num_to_split
        out = {}
        for k, v in module.items():
            rule = self._rule(k)
            if rule == "qkv":
                out[k] = _split_megatron_qkv(v, num_to_split, offset,
                                             version)
            elif rule == "row":
                out[k] = np.split(v, num_to_split, axis=0)[offset]
            elif rule == "col":
                out[k] = (np.split(v, num_to_split, axis=1)[offset]
                          if v.ndim > 1 else v)
            else:
                out[k] = v
        return {key: out} if key else out
